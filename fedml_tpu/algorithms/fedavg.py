"""FedAvg — the canonical algorithm, standalone-simulation paradigm.

Counterpart of reference fedml_api/standalone/fedavg/fedavg_api.py:12-115:
the round loop samples clients, trains each on the global weights, and
sample-weight-averages the results. Differences by design:

- the reference trains sampled clients SEQUENTIALLY with a deepcopy of the
  global state dict per client (fedavg_api.py:55-66); here the whole cohort
  trains in parallel under one ``vmap`` inside one jit — a single XLA
  program per round,
- aggregation is `tree_weighted_mean` on device (no host round-trip),
- client sampling is host-side (np, round-deterministic like the reference's
  np.random.seed(round_idx) at fedavg_api.py:83-91) and enters the program
  as a gather of the stacked client arrays.
"""

from __future__ import annotations

import logging
import time
import warnings
from collections import deque
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.rng import round_key, sample_clients, seed_everything, server_key
from fedml_tpu.core.tasks import get_task
from fedml_tpu.data import FedDataset
from fedml_tpu.models import ModelBundle, create_model
from fedml_tpu.parallel.local import (
    LocalResult,
    finalize_metrics,
    make_eval_fn,
    make_local_train_fn,
)

log = logging.getLogger(__name__)


def _donation_quiet(jitted):
    """Wrap a donate-argnums jitted step: CPU backends implement no buffer
    donation and warn once per compiled shape — donation is a no-op there,
    so the warning is noise shared by every donated round/chunk step."""
    def step(*args):
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return jitted(*args)

    return step


def _chunk_buckets(sorted_maxes, G: int, q: int, n_pad: int) -> list:
    """The ONE grouping core both bucket schedulers share (the sim paradigm's
    _round_groups over sorted client counts, the mesh paradigm's
    _mesh_group_plan over sorted per-strip maxes): split the ascending
    max-count sequence into at most ``G`` contiguous chunks, give each chunk
    the scan length of its largest member rounded up to quantum ``q`` (capped
    at ``n_pad``), and merge adjacent chunks whose scan lengths round equal.
    Returns ``[[a, b, scan_len], ...]`` half-open index chunks."""
    n = len(sorted_maxes)
    bounds = np.linspace(0, n, G + 1).round().astype(int)
    merged: list[list] = []
    for a, b in zip(bounds[:-1], bounds[1:]):
        if a == b:
            continue
        bucket = min(int(np.ceil(max(float(sorted_maxes[b - 1]), 1.0) / q) * q),
                     n_pad)
        if merged and merged[-1][2] == bucket:
            merged[-1][1] = b
        else:
            merged.append([a, b, bucket])
    return merged


class FedAvgAPI:
    """Standalone FedAvg simulator (vmap-over-clients on one chip/mesh)."""

    #: subclasses that shard round inputs themselves (cross-silo) opt out
    supports_device_data: bool = True

    def __init__(self, dataset: FedDataset, config: FedConfig, bundle: Optional[ModelBundle] = None):
        self.dataset = dataset
        self.config = config
        self.bundle = bundle or create_model(
            config.model, dataset.class_num,
            input_shape=dataset.train_x.shape[2:] or None,
        )
        self.task = get_task(dataset.task, dataset.class_num)
        #: Silo per-client exit mask (set_client_active); None = all active
        self._client_active = None
        self._client_active_version = 0
        self.root_key = seed_everything(config.seed)
        self.variables = self.bundle.init(self.root_key)
        self._local_train = self.build_local_train()
        self._eval = make_eval_fn(self.bundle, self.task)
        self.server_state = self.init_server_state()
        # the default (host-cohort) round program rides the same fedscope
        # compile telemetry + fedcost attribution hook as the packed/
        # grouped/gather programs — a vanilla run is not a blind spot.
        # Subclass paradigms build a DIFFERENT program from the same
        # __init__, so their records are name-qualified: one process running
        # several API types (bench.py) keeps one attribution per program
        # instead of latest-wins overwrites under a shared "round_step".
        from fedml_tpu.obs import timed_build

        self._round_step = timed_build(
            self._program_name("round_step"), ("default",),
            self.build_round_step)
        self._dev_train = self._maybe_place_train_data()
        self._gather_steps: dict[int, Callable] = {}
        self._group_steps: dict[tuple, Callable] = {}
        self._packed_steps: dict[tuple, Callable] = {}
        # recently computed round plans (round_idx -> (sampled, live)) —
        # stashed by _run_round_inner AND the prefetcher's background
        # builds so the fedpulse wrapper can reuse the plan the round
        # ALREADY computed instead of re-paying the O(client_num_in_total)
        # sampling draw per round (the same cost _host_round_inputs'
        # plan= parameter exists to avoid). Dict (not a single slot)
        # because pipelined builds of several FUTURE rounds race the
        # consuming round; bounded, entries popped on use.
        self._plan_stash: dict = {}
        # host round pipeline (data/pipeline.CohortPrefetcher): lazy — built
        # by the first host-path round when config.host_pipeline_depth > 0
        self._prefetcher = None
        self._donated_step = None
        # fedsched cohort scheduler: the ONE owner of per-round sampling —
        # uniform policy is bit-identical to the old sample_clients call by
        # construction; profiler policies read boundary snapshots fed by
        # run_round's notify (data/sched.py)
        from fedml_tpu.data.sched import CohortScheduler

        self._cohort_sched = CohortScheduler(
            config.cohort_policy, config.seed,
            dataset.num_clients
            if config.client_num_in_total > dataset.num_clients
            else config.client_num_in_total,
            min(config.client_num_per_round, dataset.num_clients))
        # streaming chunked host rounds (fedsched): compiled chunk programs,
        # the chunk-indexed prefetcher, and the last round's stream stats
        # (the O(1)-accumulator evidence tests and the bench read)
        self._stream_steps: dict = {}
        self._stream_pf = None
        self._stream_finish_fn = None
        self._stream_mode_memo: Optional[str] = None
        self.stream_stats: Optional[dict] = None
        #: per-round stage timings for utils/metrics.round_stats (host path)
        self._stage_rows: deque = deque(maxlen=1024)
        if self._dev_train is not None and config.stream_aggregate != "off":
            # same explicit-ignore discipline as device_data/host-pipeline:
            # the device-resident round aggregates inside its own program
            # (no host buffering to stream away), so the flags are inert
            log.warning(
                "stream_aggregate=%r (and cohort_chunk) ignored: the "
                "dataset is device-resident, so the whole-cohort round "
                "program already aggregates in-program; streaming applies "
                "to the host round path", config.stream_aggregate)
        if self._dev_train is not None:
            self._round_step_gather = timed_build(
                self._program_name("gather_step"), ("full",),
                self.build_round_step_gather)
        self.history: dict[str, list] = {"round": [], "Test/Acc": [], "Test/Loss": []}

    def _maybe_place_train_data(self):
        """Ship the full stacked client dataset to HBM once so rounds gather
        the cohort on device instead of re-shipping it from host every round
        (the reference's DataLoader contract re-materializes client data per
        round, fedavg_api.py:56-66 — on TPU that host->device hop dominates).
        Returns (train_x, train_y, train_mask, train_counts) on device or
        None when disabled/too large."""
        c = self.config
        if not self.supports_device_data or c.device_data == "off":
            if (c.device_data == "on" and not self.supports_device_data
                    and not getattr(self, "handles_own_device_data", False)):
                log.warning(
                    "device_data='on' ignored: %s shards round inputs itself; "
                    "using the host-slice path", type(self).__name__,
                )
            return None
        if type(self).build_round_step is not FedAvgAPI.build_round_step:
            # subclass rewired the round program (hierarchical/turboaggregate/
            # ...); the gather wrapper only mirrors the base body
            if c.device_data == "on":
                log.warning(
                    "device_data='on' ignored: %s overrides build_round_step, "
                    "which the gather path cannot mirror; using the host-slice "
                    "path", type(self).__name__,
                )
            return None
        x = self._eligible_device_train_x()
        if x is None:
            return None
        ds = self.dataset
        return (
            jax.device_put(x),
            jax.device_put(ds.train_y),
            jax.device_put(ds.train_mask),
            jax.device_put(jnp.asarray(ds.train_counts, jnp.float32)),
        )

    def _eligible_device_train_x(self, shard_factor: int = 1,
                                 slots_fraction: float = 1.0):
        """Shared device-residency eligibility + bf16 pre-cast for train_x.

        ``shard_factor`` = number of devices the stacked arrays will be
        sharded across (1 = fully replicated/single-device): the 'auto'
        byte budget applies to the PER-DEVICE footprint. ``slots_fraction``
        scales the estimate when the caller will truncate the record axis
        before placement (the grouped mesh schedule keeps only each group's
        scan length, so its footprint is sum(n_g * len_g) / (C * n_pad) of
        the full stack). Auto also declines CPU backends — there is no
        host->device hop to avoid, and a second in-RAM copy of the dataset
        would be pure cost ('on' still forces it, e.g. for tests). Returns
        train_x (bf16-cast when training in bf16) or None when ineligible."""
        c = self.config
        ds = self.dataset
        if getattr(ds, "virtual", False):
            # cross-device scale: the client stack does not exist; rounds
            # materialize O(cohort) slices host-side (data/crossdevice.py)
            if c.device_data == "on":
                log.warning(
                    "device_data='on' ignored: %s is a virtual cross-device "
                    "dataset (%d clients); using the sampled host-slice path",
                    ds.name, ds.num_clients)
            return None
        x = ds.train_x
        cast_bf16 = c.dtype == "bfloat16" and np.issubdtype(x.dtype, np.floating)
        nbytes = ((x.size * 2 if cast_bf16 else x.nbytes) + ds.train_y.nbytes
                  + ds.train_mask.nbytes + ds.train_counts.nbytes)
        nbytes *= slots_fraction
        if c.device_data == "auto" and (
            jax.default_backend() == "cpu"
            or nbytes / max(shard_factor, 1) > c.device_data_max_bytes
        ):
            return None
        if cast_bf16:
            from fedml_tpu.utils.dtypes import host_bf16_cast

            return host_bf16_cast(x, c.dtype)
        return x

    # -- factory methods subclasses override ---------------------------------

    def _local_train_kwargs(self) -> dict:
        """The ONE config->trainer kwargs mapping (parallel/local.py
        local_train_kwargs), shared by every build_local_train — subclasses
        add to it rather than re-listing it, so a new config knob cannot be
        silently dropped by one algorithm."""
        from fedml_tpu.parallel.local import local_train_kwargs

        return local_train_kwargs(self.config)

    def build_local_train(self):
        return make_local_train_fn(self.bundle, self.task,
                                   **self._local_train_kwargs())

    def init_server_state(self):
        """State threaded through aggregate() across rounds (FedOpt's server
        optimizer moments, FedNova's momentum buffer, ...). {} = stateless."""
        return {}

    def crosssilo_hooks(self) -> Optional[dict]:
        """Mesh-path translation of this algorithm's ``aggregate``: a dict of
        make_crosssilo_round hooks (client_transform / reduce_extras /
        server_update) or None for the plain weighted psum. Algorithms whose
        aggregation is more than a weighted mean implement this so their
        CrossSilo* variant runs in-mesh (the counterpart of the reference's
        one-Aggregator-subclass-per-algorithm MPI deployments, e.g.
        FedOptAggregator.py:70-120). Only consulted by the cross-silo
        paradigm's build_round_step."""
        return None

    def aggregate(self, variables, stacked_vars, counts, infos: LocalResult, rng, server_state):
        """Weighted average (fedavg_api.py:100-115). Subclasses change this.
        Returns (new_variables, new_server_state); must be jit-pure."""
        return tree_weighted_mean(stacked_vars, counts), server_state

    def _cohort_train(self, variables, cx, cy, cm, counts, keys) -> LocalResult:
        """Train a stacked cohort: one vmap (default), or — with
        config.cohort_vmap_width = k > 0 — lax.map over chunks of k vmapped
        clients. The chunked schedule computes the exact same per-client
        results in the same stacking order; it exists because the full vmap
        fuses all clients' convs into one grouped convolution whose TPU
        lowering pads cohort-fold (docs/mfu_experiments.md H4)."""
        vt = jax.vmap(self._local_train, in_axes=(None, 0, 0, 0, 0, 0))
        n = cx.shape[0]
        w = self.config.cohort_vmap_width
        if w <= 0 or w >= n or n % w:
            if 0 < w < n and n % w and not getattr(self, "_warned_cohort_width", False):
                log.warning(
                    "cohort_vmap_width=%d does not divide a cohort/group of "
                    "%d clients; falling back to the full vmap schedule for "
                    "such groups", w, n)
                # warn-once bookkeeping on a shape-static branch: executes at
                # trace time only and never feeds a traced value
                self._warned_cohort_width = True  # fedlint: disable=traced-purity
            return vt(variables, cx, cy, cm, counts, keys)

        def rs(a):
            return a.reshape((n // w, w) + a.shape[1:])

        res = jax.lax.map(
            lambda args: vt(variables, *args),
            (rs(cx), rs(cy), rs(cm), rs(counts), rs(keys)),
        )
        return jax.tree.map(lambda a: a.reshape((n,) + a.shape[2:]), res)

    def _round_body(self, variables, server_state, cx, cy, cm, counts, rng):
        res = self._cohort_train(
            variables, cx, cy, cm, counts, jax.random.split(rng, cx.shape[0])
        )
        return self._finish_round(variables, server_state, res, counts, rng)

    def _finish_round(self, variables, server_state, res, counts, rng):
        """Aggregate the cohort's local results + elastic-round guard +
        weighted train loss (shared by the single- and multi-group round
        programs)."""
        new_vars, new_state = self.aggregate(
            variables, res.variables, counts, res, server_key(rng), server_state
        )
        # elastic rounds: failed clients enter with count 0 and drop out of
        # the weighted mean; an all-failed round is a full no-op — weights
        # AND server state (FedOpt moments etc.) roll back, else the server
        # optimizer would absorb the garbage zero-aggregate pseudo-gradient
        total = jnp.sum(counts)
        keep = total > 0
        new_vars = jax.tree.map(lambda n, o: jnp.where(keep, n, o), new_vars, variables)
        new_state = jax.tree.map(lambda n, o: jnp.where(keep, n, o), new_state, server_state)
        train_loss = jnp.sum(res.train_loss * counts) / jnp.maximum(total, 1e-12)
        if self._lens_armed:
            # fedlens lane (obs/lens.py): output-only reductions over the
            # stacked cohort result the program already holds — nothing
            # here feeds new_vars/new_state, so an armed program computes
            # bit-identical weights (pinned by tests/test_lens.py)
            from fedml_tpu.obs.lens import stacked_lens

            return (new_vars, new_state, train_loss,
                    stacked_lens(variables, res, counts))
        return new_vars, new_state, train_loss

    def build_round_step(self):
        body = self._round_body

        @jax.jit
        def round_step(variables, server_state, cx, cy, cm, counts, rng):
            return body(variables, server_state, cx, cy, cm, counts, rng)

        return round_step

    def build_round_step_gather(self, bucket: Optional[int] = None):
        """Round step over device-resident data: the sampled cohort enters as
        an index vector; the gather happens in HBM inside the same program.
        ``live`` [cohort] zeroes failed clients' weights (elastic rounds).
        ``bucket`` (static) truncates the per-client record axis to the
        cohort's real maximum — loaders put real records first, so the tail
        holds no real data and the trimmed steps were masked no-ops (the
        epoch shuffle stream does change with the axis length; see
        FedConfig.bucket_quantum_batches)."""
        body = self._round_body

        @jax.jit
        def round_step(variables, server_state, tx, ty, tm, tcounts, idx, live, rng):
            cx = jnp.take(tx, idx, axis=0)
            cy = jnp.take(ty, idx, axis=0)
            cm = jnp.take(tm, idx, axis=0)
            if bucket is not None:
                cx, cy, cm = cx[:, :bucket], cy[:, :bucket], cm[:, :bucket]
            counts = jnp.take(tcounts, idx, axis=0) * live
            return body(variables, server_state, cx, cy, cm, counts, rng)

        return round_step

    def _round_bucket(self, sampled: np.ndarray, live: Optional[np.ndarray]) -> Optional[int]:
        """Static scan length for this round: max real count over the live
        cohort, rounded up to bucket_quantum_batches*batch_size. None = use
        the global n_pad (bucketing off, or nothing to trim)."""
        c = self.config
        n_pad = int(self.dataset.train_x.shape[1])
        q = c.bucket_quantum_batches * c.batch_size
        if c.bucket_quantum_batches <= 0 or q >= n_pad:
            return None
        counts = np.asarray(self.dataset.train_counts, np.float64)[sampled]
        if live is not None:
            counts = counts * live
        maxc = float(counts.max()) if counts.size else 0.0
        bucket = int(np.ceil(max(maxc, 1.0) / q) * q)
        return None if bucket >= n_pad else bucket

    def _round_groups(self, sampled: np.ndarray, live: Optional[np.ndarray]):
        """Multi-group schedule (config.bucket_groups > 1): sort the cohort
        by real count and split it into up to ``bucket_groups`` contiguous
        groups, each with its own quantum-rounded scan length. A single
        scan length must cover the cohort's LARGEST client, so small
        clients burn (max - count) masked padding steps; per-group scan
        lengths cut that waste while computing the exact same weighted
        aggregate (group order is irrelevant to a weighted mean).

        Returns None (schedule degenerates to the single-bucket path) or
        ``(perm, groups)``: ``perm`` sorts cohort positions by count,
        ``groups`` is a tuple of (size, scan_len) ascending."""
        c = self.config
        if c.bucket_groups <= 1 or len(sampled) < 2:
            return None
        n_pad = int(self.dataset.train_x.shape[1])
        q = c.bucket_quantum_batches * c.batch_size
        if c.bucket_quantum_batches <= 0 or q >= n_pad:
            return None
        counts = np.asarray(self.dataset.train_counts, np.float64)[sampled]
        if live is not None:
            counts = counts * live
        perm = np.argsort(counts, kind="stable")
        chunks = _chunk_buckets(counts[perm], min(c.bucket_groups, len(sampled)),
                                q, n_pad)
        groups = [(b - a, bucket) for a, b, bucket in chunks]
        if len(groups) == 1:
            # degenerate schedule: one shared scan length — the single-bucket
            # path computes the identical program (same bucket via
            # _round_bucket, same per-position keys), so don't compile a
            # second copy of it here
            return None
        return perm, tuple((s, b) for s, b in groups)

    def build_round_step_gather_groups(self, groups: tuple):
        """Round step over device-resident data with PER-GROUP scan lengths
        (see _round_groups). ``idx``/``live`` arrive in group (count-sorted)
        order; ``pos`` maps each slot back to its original sampled position
        so every client consumes the same per-round RNG key it would under
        the single-bucket program (key = split(rng, cohort)[position])."""
        cohort_train = self._cohort_train
        finish = self._finish_round
        sizes = [g[0] for g in groups]
        buckets = [g[1] for g in groups]
        starts = np.concatenate([[0], np.cumsum(sizes)[:-1]]).astype(int)
        cohort = int(sum(sizes))

        @jax.jit
        def round_step(variables, server_state, tx, ty, tm, tcounts, idx, live, pos, rng):
            keys = jax.random.split(rng, cohort)[pos]
            parts = []
            for start, size, bucket in zip(starts, sizes, buckets):
                sl = slice(start, start + size)
                idx_g = idx[sl]
                cx = jnp.take(tx, idx_g, axis=0)[:, :bucket]
                cy = jnp.take(ty, idx_g, axis=0)[:, :bucket]
                cm = jnp.take(tm, idx_g, axis=0)[:, :bucket]
                cnt_g = jnp.take(tcounts, idx_g, axis=0) * live[sl]
                parts.append(cohort_train(variables, cx, cy, cm, cnt_g, keys[sl]))
            res = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *parts)
            counts = jnp.take(tcounts, idx, axis=0) * live
            return finish(variables, server_state, res, counts, rng)

        return round_step

    def _program_name(self, base: str) -> str:
        """Telemetry/attribution name for a round program built in the
        shared ``__init__``: subclasses build a DIFFERENT program from the
        same code path, so qualify by class. Base-class instances keep the
        bare name (existing counter keys and goldens unchanged)."""
        if type(self) is FedAvgAPI:
            return base
        return f"{base}.{type(self).__name__}"

    def _lru_step(self, cache: dict, key, builder, name: str, cap: int = 64):
        """Shared LRU for compiled round programs (group/packed schedules):
        bound the cache — with failure injection the per-round plan varies
        and the key space is large — and make every eviction VISIBLE
        (history counter + log), since each one implies a fresh XLA compile
        (minutes through a remote-compile tunnel) next time the key recurs;
        a pathological config shows up here instead of as mystery slowness.
        Dict order is recency: hits re-insert, eviction pops the oldest.

        Builds route through fedscope compile telemetry (obs/compile): the
        "compile" registry group counts hits/misses and the traced runs get
        build + first-call spans keyed by the program's shape key."""
        from fedml_tpu.obs import record_cache_hit, timed_build

        # class-qualified like the __init__-built programs: a subclass's
        # packed/group/gather program is a different program and must not
        # overwrite the base class's attribution record or merge counters
        name = self._program_name(name)
        step = cache.get(key)
        if step is None:
            if len(cache) >= cap:
                cache.pop(next(iter(cache)))
                n_evict = self.history.get(f"{name}_evictions", 0) + 1
                self.history[f"{name}_evictions"] = n_evict
                log.info("%s cache full: evicted 1 of %d compiled round "
                         "programs (total evictions %d)", name, cap, n_evict)
            step = cache[key] = timed_build(name, key, builder)
        else:
            cache[key] = cache.pop(key)
            record_cache_hit(name)
        return step

    # -- packed schedule (parallel/packed.py) --------------------------------

    def _packing_hooks(self) -> Optional[dict]:
        """The packed schedule's algorithm contract (packed-everywhere):
        the weighted mean folds INTO the lane scan, and everything beyond
        it rides the SAME three-hook contract the mesh paradigm uses
        (crosssilo_hooks: client_transform at lane emit, reduce_extras
        accumulated in the scan, server_update post-aggregation with
        threaded server state). Returns ``{}`` for plain weighted-mean
        algorithms, the hook dict for the zoo (FedOpt/FedNova/AGC/robust —
        hooks now live on the BASE algorithm classes), or None when
        packing cannot mirror this subclass (rewired build_local_train,
        or a custom aggregate() with no hook translation)."""
        if type(self).build_local_train is not FedAvgAPI.build_local_train:
            if not getattr(self, "_warned_no_pack", False):
                log.warning(
                    "pack_lanes=%d ignored: %s rewires build_local_train, "
                    "which the packed lane builder cannot mirror",
                    self.config.pack_lanes, type(self).__name__)
                self._warned_no_pack = True
            return None
        hooks = self.crosssilo_hooks()
        if hooks is None:
            if type(self).aggregate is not FedAvgAPI.aggregate:
                if not getattr(self, "_warned_no_pack", False):
                    log.warning(
                        "pack_lanes=%d ignored: %s overrides aggregate() "
                        "without crosssilo hooks", self.config.pack_lanes,
                        type(self).__name__)
                    self._warned_no_pack = True
                return None
            hooks = {}
        return hooks

    def _packing_supported(self) -> bool:
        return self._packing_hooks() is not None

    def packed_status(self) -> dict:
        """Introspection for the packed-coverage contract (the tier-1
        matrix test pins it): ``{"scheduled": <packed schedule applies>,
        "packed_conv_active": <joint MXU form engages>, "reason": <None or
        the documented fallback reason>}``. After packed-everywhere the
        only honest reasons left are the DESIGN.md §15 exception table —
        models without a packed twin, flax-rng dropout without an
        explicit-key twin, flag off, or an algorithm the lane builder
        cannot mirror."""
        from fedml_tpu.parallel.packed import packed_fallback_reason

        c = self.config
        if c.pack_lanes <= 0:
            return {"scheduled": False, "packed_conv_active": False,
                    "reason": "pack_lanes=0"}
        if not self._packing_supported():
            return {"scheduled": False, "packed_conv_active": False,
                    "reason": f"{type(self).__name__} has no packed-lane "
                              "algorithm mirror"}
        reason = packed_fallback_reason(self.bundle, c.packed_conv,
                                        c.client_optimizer)
        return {"scheduled": True, "packed_conv_active": reason is None,
                "reason": reason}

    def _packed_plan(self, sampled: np.ndarray):
        from fedml_tpu.parallel.packed import plan_packing

        key = tuple(int(s) for s in sampled)
        memo = getattr(self, "_packed_plan_memo", None)
        if memo is not None and memo[0] == key:
            return memo[1]   # run_round + round_counts share one build
        c = self.config
        counts = np.asarray(self.dataset.train_counts, np.float64)[sampled]
        # finer quantum than the bucketed schedule: a lane amortizes its
        # rounding tail over several clients, and the tail is pure waste —
        # the quantum only bounds how many distinct XLA programs the
        # varying per-round plans can demand (LRU-capped anyway)
        plan = plan_packing(counts, c.batch_size, c.epochs, c.pack_lanes,
                            t_quantum=max(1, c.bucket_quantum_batches // 4))
        self._packed_plan_memo = (key, plan)
        return plan

    def build_round_step_packed(self, shape_key: tuple):
        from fedml_tpu.parallel.crosssilo import apply_server_and_rollback
        from fedml_tpu.parallel.packed import (impl_label,
                                               make_packed_cohort_train,
                                               packed_conv_active,
                                               resolve_packed_conv)

        c = self.config
        n_pad = int(self.dataset.train_x.shape[1])
        hooks = self._packing_hooks() or {}
        server_update = hooks.get("server_update")
        has_extras = hooks.get("reduce_extras") is not None
        # fedplan: 'auto' resolves HERE, at program-build time, against the
        # schedule's actual lane count — the plan (or a concrete flag)
        # flows to the builder and rides the cost hints below
        pconv = resolve_packed_conv(c.packed_conv, self.bundle,
                                    int(shape_key[0]),
                                    optimizer=c.client_optimizer)
        lens_on = self._lens_armed
        packed = make_packed_cohort_train(
            self.bundle, self.task, n_pad, shape_key,
            packed_conv=pconv,
            client_transform=hooks.get("client_transform"),
            reduce_extras=hooks.get("reduce_extras"),
            lens=lens_on,
            **self._local_train_kwargs())

        @jax.jit
        def round_step(variables, server_state, tx, ty, tm, rows, weights,
                       rng, plan_arrays):
            out = packed(
                variables, tx, ty, tm, rows, weights, rng, plan_arrays)
            acc, acc_w, acc_loss, _tau, extras = out[:5]
            denom = jnp.maximum(acc_w, 1e-12)
            agg = jax.tree.map(
                lambda a, v: (a / denom).astype(v.dtype), acc, variables)
            # the one shared post-aggregation tail (crosssilo.py): server
            # hook on the aggregate with the round's server key, elastic
            # all-failed rollback of weights AND server state
            new_vars, new_state = apply_server_and_rollback(
                variables, agg, extras if has_extras else None, acc_w,
                server_state, rng, server_update)
            if lens_on:
                from fedml_tpu.obs.lens import packed_lens

                upd, lf, ll, mw = out[5]
                return (new_vars, new_state, acc_loss / denom,
                        packed_lens(upd, lf, ll, mw))
            return new_vars, new_state, acc_loss / denom

        # fedcost packing hint (obs/cost.attribute_program): the joint
        # form's block-diag dots stream n_lanes x the useful FLOPs; the
        # per-lane vmap form's grouped convs fold the same n_lanes clients
        # (H4) — either way the program folds shape_key[0] clients per op
        active = packed_conv_active(self.bundle, pconv, c.client_optimizer)
        round_step.cost_hints = {
            "packed_conv": impl_label(pconv) if active else "off",
            "packing_factor": int(shape_key[0])}
        if active and not isinstance(pconv, str):
            # the LoweringPlan itself: attribute_program self-checks the
            # realized static ceiling against it and emits program_plan
            round_step.cost_hints["plan"] = pconv
        return round_step

    def _run_packed_round(self, sampled, live, rk, round_idx=0):
        """Execute the round under the packed schedule; returns (variables,
        server_state, loss) or None when packing doesn't apply this round.
        ``live`` already folds the Silo client-active mask (_round_plan);
        exited clients additionally get the STRUCTURAL lane freeze — their
        plan steps masked dead (mask_plan_arrays) in the same compiled
        program, never a vmap fallback."""
        if not self._packing_supported():
            return None
        plan = self._packed_plan(sampled)
        if plan is None:
            return None
        key = plan.shape_key
        step = self._lru_step(self._packed_steps, key,
                              lambda: self.build_round_step_packed(key),
                              "packed_step")
        counts = np.asarray(self.dataset.train_counts, np.float32)[sampled]
        weights = counts if live is None else counts * np.asarray(live, np.float32)
        active = self._client_active
        if active is None:
            from fedml_tpu.parallel.packed import plan_arrays_tuple

            plan_arrays = plan_arrays_tuple(plan)
        else:
            from fedml_tpu.parallel.packed import mask_plan_arrays

            plan_arrays = mask_plan_arrays(
                plan, np.asarray(active, np.float32)[sampled][plan.member_pos])
        tx, ty, tm, _tc = self._dev_train
        out = step(self.variables, self.server_state, tx, ty, tm,
                   jnp.asarray(sampled, jnp.int32), jnp.asarray(weights),
                   rk, tuple(jnp.asarray(a) for a in plan_arrays))
        if len(out) == 4:
            # packed_lens flattens [n_lanes, k_max] in member_pos order;
            # padding slots (member_valid 0) and dead/exited members
            # (weight 0) are dropped host-side via the valid mask
            mp = np.asarray(plan.member_pos, np.int64).reshape(-1)
            mv = np.asarray(plan_arrays[7], np.float64).reshape(-1)
            valid = (mv > 0) & (np.asarray(weights, np.float64)[mp] > 0)
            out = self._lens_absorb(round_idx, out,
                                    np.asarray(sampled, np.int64)[mp], valid)
        return out

    def _sample_failures(self, round_idx: int, cohort: int,
                         record: bool = True) -> Optional[np.ndarray]:
        """Deterministic per-round fault injection (SURVEY.md §5.3: the
        reference has NO failure detection or fault injection — its only
        failure handling is MPI.Abort). With ``config.failure_prob`` > 0
        each sampled client independently fails this round; the aggregation
        then runs elastically over the survivors. Returns a {0,1} live
        vector or None when injection is off. ``record=False`` computes the
        same deterministic outcome without logging/history side effects
        (for :meth:`round_counts`)."""
        p = self.config.failure_prob
        if not p:
            return None
        elastic_ok = (type(self).build_round_step is FedAvgAPI.build_round_step
                      or getattr(type(self), "elastic_rounds_ok", False))
        if not elastic_ok:
            if not getattr(self, "_warned_no_elastic", False):
                log.warning(
                    "failure_prob=%s ignored: %s rewires the round program "
                    "without an elastic (zero-weight) aggregation guard",
                    p, type(self).__name__)
                self._warned_no_elastic = True
            return None
        rng = np.random.default_rng([self.config.seed, 0x0F41, round_idx])
        live = (rng.random(cohort) >= p).astype(np.float32)
        if record:
            n_failed = int(cohort - live.sum())
            if n_failed:
                log.info("round %d: %d/%d clients failed (injected)",
                         round_idx, n_failed, cohort)
            self.history.setdefault("failed_clients", []).append(n_failed)
        return live

    def set_client_active(self, active) -> None:
        """Per-client participation mask (the Silo harness's per-client
        early EXIT, algorithms/silo.py): a client whose entry is 0 stops
        contributing — its aggregation weight zeroes on every schedule,
        and the packed paths additionally freeze its lane span structurally
        (parallel/packed.mask_plan_arrays) inside the SAME compiled
        program. ``active``: [num_clients] {0,1}-ish, or None to clear.
        Takes effect from the next round (next superstep BLOCK on the
        packed-mesh superstep path — the block is one device program)."""
        if active is None:
            self._client_active = None
        else:
            a = np.asarray(active, np.float32)
            self._client_active = None if a.all() else a
        self._client_active_version += 1

    def _round_plan(self, round_idx: int, record: bool = False):
        """The deterministic per-round plan: (sampled cohort, live mask,
        scan bucket). run_round executes exactly this plan; round_counts
        reports it — one source of truth for what a round trains on.
        The Silo client-active mask folds into ``live`` here, so every
        host-cohort/gather/grouped/packed schedule honors an exit the same
        way it honors an injected failure: weight zero."""
        sampled = self._cohort_sched.sample(round_idx)
        live = self._sample_failures(round_idx, len(sampled), record=record)
        if self._client_active is not None:
            av = self._client_active[sampled]
            live = av if live is None else live * av
        bucket = self._round_bucket(sampled, live)
        return sampled, live, bucket

    def round_counts(self, round_idx: int) -> tuple:
        """(real, padded) training examples one epoch of this round
        processes: real = the live cohort's actual record counts (masked
        padding excluded; failed clients' work is discarded by aggregation,
        so it isn't "real" training), padded = the scan slots the device
        EXECUTES — every sampled client counts (failure injection only
        zeroes weights), at the shared scan length, or per-group
        size x scan_len when bucket_groups schedules apply. Used by
        bench.py so throughput accounting can never drift from run_round."""
        sampled, live, bucket = self._round_plan(round_idx)
        counts = np.asarray(self.dataset.train_counts, np.float64)[sampled]
        if live is not None:
            counts = counts * live
        n_pad = int(self.dataset.train_x.shape[1])
        if (self.config.pack_lanes > 0 and self._dev_train is not None
                and self._packing_supported()):
            pk = self._packed_plan(sampled)
            if pk is not None:
                # packed lanes execute T batch-steps each over the whole
                # round; report one epoch's share, rounded to nearest
                # (exact at epochs=1, the bench recipe; off by <1 batch
                # otherwise — advisor r4 #3)
                ep = max(self.config.epochs, 1)
                padded = round(pk.executed_slots / ep) * self.config.batch_size
                return int(counts.sum()), int(padded)
        if (self._dev_train is None and self._stream_mode() != "off"
                and self._stream_packed_active()):
            # streamed packed chunks: each chunk executes its own lane
            # plan's slots — sum them, one epoch's share (as above)
            from fedml_tpu.parallel.packed import plan_packing

            c = self.config
            ep = max(c.epochs, 1)
            raw = np.asarray(self.dataset.train_counts, np.float64)[sampled]
            padded = 0
            for start, size in self._stream_chunk_spec(len(sampled)):
                pk = plan_packing(
                    raw[start:start + size], c.batch_size, c.epochs,
                    c.pack_lanes,
                    t_quantum=max(1, c.bucket_quantum_batches // 4))
                if pk is not None:
                    padded += round(pk.executed_slots / ep) * c.batch_size
            return int(counts.sum()), int(padded)
        plan = self._round_groups(sampled, live) if self._dev_train is not None else None
        if plan is not None:
            padded = sum(s * b for s, b in plan[1])
        else:
            padded = (n_pad if bucket is None else bucket) * len(sampled)
        return int(counts.sum()), int(padded)

    # -- host round pipeline -------------------------------------------------

    def _host_round_inputs(self, round_idx: int, pool=None, n_chunks: int = 0,
                           plan=None):
        """Host-side inputs for one non-device-resident round — the ONE
        builder the serial path and the prefetcher share, so the pipeline
        cannot drift from the serial path: materialize the sampled cohort,
        trim it to the round's bucket, bf16-cast on host, zero failed
        clients' aggregation weights. Pure in (seed, round_idx); ``plan``
        passes an already-computed ``_round_plan`` result (the serial call
        site has one — sampling draws O(client_num_in_total) per call)."""
        from fedml_tpu.data.pipeline import materialize_cohort
        from fedml_tpu.utils.dtypes import host_bf16_cast

        if plan is not None:
            sampled, live, bucket = plan
        else:
            # prefetcher path: this build's plan is the one the consuming
            # round's pulse hook will want — stash it so pulse-on pipelined
            # runs don't re-pay the sampling draw on the critical path
            sampled, live, bucket = self._round_plan(round_idx)
            self._stash_plan(round_idx, sampled, live)
        cx, cy, cm, counts = materialize_cohort(
            self.dataset, sampled, pool, n_chunks)
        if bucket is not None:
            cx, cy, cm = cx[:, :bucket], cy[:, :bucket], cm[:, :bucket]
        # bf16 training casts on device anyway — casting on HOST first
        # halves the per-round uplink (the dominant cost for big-input
        # host-path rounds, e.g. the 342k-client cross-device row's
        # 140 MB/round of 10k-dim features)
        cx = host_bf16_cast(np.asarray(cx), self.config.dtype)
        counts = np.asarray(counts, np.float32)
        if live is not None:
            counts = counts * live
        return cx, cy, cm, counts

    def _prefetch_build(self, round_idx: int, pool):
        """Background stage of the host round pipeline: materialize + cast
        (fanned out over the cohort's clients on ``pool``), then ship
        host->device — all while the in-flight round computes. Returns the
        device-resident payload plus stage timings (round_stats)."""
        from fedml_tpu.obs import tracer_if_sampled

        # the prefetch spans belong to the round they build for, so they
        # follow that round's head-sampling verdict (same pure function)
        tr = tracer_if_sampled(0, round_idx)
        t0 = time.perf_counter()
        if tr is None:
            cx, cy, cm, counts = self._host_round_inputs(
                round_idx, pool, n_chunks=getattr(pool, "_max_workers", 0))
            t1 = time.perf_counter()
            payload = (jax.device_put(cx), jax.device_put(cy),
                       jax.device_put(cm), jax.device_put(counts))
            jax.block_until_ready(payload)
        else:
            # these spans live on the prefetcher's background threads — in
            # the timeline they sit beside (not under) the consuming round,
            # which is exactly the overlap the pipeline exists to create
            with tr.span("materialize", cat="prefetch",
                         args={"round": round_idx}):
                cx, cy, cm, counts = self._host_round_inputs(
                    round_idx, pool, n_chunks=getattr(pool, "_max_workers", 0))
            t1 = time.perf_counter()
            with tr.span("h2d", cat="prefetch", args={"round": round_idx}):
                payload = (jax.device_put(cx), jax.device_put(cy),
                           jax.device_put(cm), jax.device_put(counts))
                jax.block_until_ready(payload)
        t2 = time.perf_counter()
        return payload, {"materialize_ms": (t1 - t0) * 1e3,
                         "h2d_ms": (t2 - t1) * 1e3}

    def _host_prefetcher(self):
        """The lazy CohortPrefetcher for the host round path; None when the
        pipeline is off (depth 0) or rounds are device-resident."""
        c = self.config
        if c.host_pipeline_depth <= 0 or self._dev_train is not None:
            return None
        if self._prefetcher is None:
            from fedml_tpu.data.pipeline import CohortPrefetcher

            # speculate within the training schedule only — train() pops
            # rounds [0, comm_round), so building past the end is pure
            # waste; a driver that pops beyond it (the bench re-runs
            # [1, comm_round]) raises the bound itself
            self._prefetcher = CohortPrefetcher(
                self._prefetch_build, c.host_pipeline_depth,
                workers=c.host_pipeline_workers,
                max_round=c.comm_round)
        return self._prefetcher

    def _host_pipeline_step(self):
        """Round step for the pipeline path. When this API runs the base
        round program, the cohort buffers are DONATED (config.donate): the
        round step is their last consumer, so the runtime reclaims the
        fixed-shape (bucketed) blocks during execution and the allocator
        hands them to the next round's device_put instead of growing the
        live footprint by pipeline depth. Subclasses that rewire
        build_round_step keep their own (non-donating) step."""
        if (not self.config.donate
                or type(self).build_round_step is not FedAvgAPI.build_round_step):
            return self._round_step
        if self._donated_step is None:
            from fedml_tpu.obs import timed_build

            jitted = timed_build(
                self._program_name("donated_step"), ("donated",),
                lambda: jax.jit(self._round_body, donate_argnums=(2, 3, 4)))
            self._donated_step = _donation_quiet(jitted)
        return self._donated_step

    # -- streaming chunked host rounds (fedsched) ----------------------------

    def _stream_mode(self) -> str:
        """Effective streaming-aggregation mode for THIS API: the config
        mode when the base round machinery applies, else "off" with one
        warning — streaming folds a plain weighted mean, so a rewired
        local trainer / round program / custom aggregate() keeps its batch
        path (the same exception discipline as the packed schedule)."""
        memo = self._stream_mode_memo
        if memo is not None:
            return memo
        c = self.config
        mode = c.stream_aggregate
        if mode != "off" and (
                type(self).aggregate is not FedAvgAPI.aggregate
                or self.crosssilo_hooks() is not None
                or type(self).build_local_train is not FedAvgAPI.build_local_train
                or type(self).build_round_step is not FedAvgAPI.build_round_step):
            log.warning(
                "stream_aggregate=%r ignored: %s rewires aggregation (or "
                "carries crosssilo hooks) or the round program, which the "
                "streaming fold cannot mirror; using the batch path",
                mode, type(self).__name__)
            mode = "off"
        self._stream_mode_memo = mode
        return mode

    def _stream_packed_active(self) -> bool:
        """Whether streamed chunks ride the packed-lanes round program
        (pack_lanes > 0): clients packed back-to-back in scan lanes, so a
        chunk executes ~ceil(count/bs) real batches per client instead of
        the shared bucket length."""
        return self.config.pack_lanes > 0 and self._stream_mode() != "off"

    def _counts_view(self, dtype) -> "np.ndarray":
        """Cached float view of the population counts table: the streamed
        chunk path indexes it once per sub-cohort and the pulse feed once
        per round, so a million-client table is converted once per run,
        not re-cast (~8 MB of memcpy) on every lookup."""
        cache = getattr(self, "_counts_view_cache", None)
        if cache is None:
            cache = self._counts_view_cache = {}
        src = self.dataset.train_counts
        key = (id(src), np.dtype(dtype).name)
        v = cache.get(key)
        if v is None:
            if any(k[0] != id(src) for k in cache):
                cache.clear()    # dataset swapped: drop the old table's views
            v = cache[key] = np.asarray(src, dtype)
        return v

    @property
    def _stream_chunks_per_round(self) -> int:
        c = self.config
        cohort = min(c.client_num_per_round, self.dataset.num_clients)
        if c.cohort_chunk <= 0 or c.cohort_chunk >= cohort:
            return 1
        return -(-cohort // c.cohort_chunk)

    def _stream_chunk_spec(self, cohort_n: int) -> list:
        """[(start, size)] half-open sub-cohort chunks in plan order."""
        chunk = self.config.cohort_chunk
        if chunk <= 0 or chunk >= cohort_n:
            return [(0, cohort_n)]
        return [(s, min(chunk, cohort_n - s))
                for s in range(0, cohort_n, chunk)]

    def _stream_chunk_inputs(self, round_idx: int, ci: int, pool=None,
                             n_chunks: int = 0):
        """Host-side inputs for ONE sub-cohort chunk — pure in
        (seed, round_idx, ci) like _host_round_inputs: materialize just the
        chunk's clients, trim to the ROUND's shared bucket (vmap chunks —
        the packed program needs the full record axis for its canonical
        replay tables), bf16-cast, zero failed clients' weights, and derive
        the full-round-normalized aggregation weights the deterministic
        fold needs (the total weight is known from the plan, so the fold
        can use exactly tree_weighted_mean's normalize-first arithmetic)."""
        from fedml_tpu.data.pipeline import materialize_cohort
        from fedml_tpu.utils.dtypes import host_bf16_cast

        sampled, live, bucket = self._round_plan(round_idx)
        if ci == 0:
            self._stash_plan(round_idx, sampled, live)
        start, size = self._stream_chunk_spec(len(sampled))[ci]
        packed = self._stream_packed_active()
        cx, cy, cm, counts = materialize_cohort(
            self.dataset, sampled[start:start + size], pool, n_chunks)
        if bucket is not None and not packed:
            cx, cy, cm = cx[:, :bucket], cy[:, :bucket], cm[:, :bucket]
        cx = host_bf16_cast(np.asarray(cx), self.config.dtype)
        counts = np.asarray(counts, np.float32)
        w_full = self._counts_view(np.float32)[sampled]
        if live is not None:
            lv = np.asarray(live, np.float32)
            counts = counts * lv[start:start + size]
            w_full = w_full * lv
        # f32 normalize-first, bit-matching tree_weighted_mean's
        # w / max(sum(w), 1e-12): the weights are integer-valued f32, so
        # the host sum is exact and order-free
        denom = np.maximum(np.float32(w_full.sum()), np.float32(1e-12))
        w_norm = (counts / denom).astype(np.float32)
        return (cx, cy, cm, counts, w_norm), (len(sampled), start, size,
                                              bucket)

    def _stream_prefetch_build(self, gidx: int, pool):
        """Background build for global chunk index ``gidx`` = round *
        chunks_per_round + chunk — the CohortPrefetcher speculates over
        this monotone sequence exactly as it does over rounds, so its
        in-flight memory is depth x ONE CHUNK, never a whole cohort."""
        from fedml_tpu.obs import tracer_if_sampled

        C = self._stream_chunks_per_round
        r, ci = divmod(gidx, C)
        tr = tracer_if_sampled(0, r)
        t0 = time.perf_counter()
        if tr is None:
            payload_np, meta = self._stream_chunk_inputs(
                r, ci, pool, n_chunks=getattr(pool, "_max_workers", 0))
            t1 = time.perf_counter()
            payload = tuple(jax.device_put(a) for a in payload_np)
            jax.block_until_ready(payload)
        else:
            with tr.span("materialize", cat="prefetch",
                         args={"round": r, "chunk": ci}):
                payload_np, meta = self._stream_chunk_inputs(
                    r, ci, pool, n_chunks=getattr(pool, "_max_workers", 0))
            t1 = time.perf_counter()
            with tr.span("h2d", cat="prefetch",
                         args={"round": r, "chunk": ci}):
                payload = tuple(jax.device_put(a) for a in payload_np)
                jax.block_until_ready(payload)
        t2 = time.perf_counter()
        return (payload, meta), {"materialize_ms": (t1 - t0) * 1e3,
                                 "h2d_ms": (t2 - t1) * 1e3}

    def _stream_prefetcher(self):
        """Chunk-granular CohortPrefetcher for the streaming round path
        (depth counts CHUNKS, so memory in flight is depth sub-cohorts)."""
        c = self.config
        if c.host_pipeline_depth <= 0:
            return None
        if self._stream_pf is None:
            from fedml_tpu.data.pipeline import CohortPrefetcher

            C = self._stream_chunks_per_round
            self._stream_pf = CohortPrefetcher(
                self._stream_prefetch_build, c.host_pipeline_depth,
                workers=c.host_pipeline_workers,
                max_round=(None if c.comm_round is None
                           else c.comm_round * C),
                name="stream-prefetch")
        return self._stream_pf

    def build_round_step_stream_chunk(self, cohort: int, start: int,
                                      size: int):
        """One sub-cohort's jitted streaming step: train the chunk under
        the SAME vmap schedule as the batch round (per-client keys =
        split(rng, cohort)[position] — identical per-client math), then
        fold its normalize-first weighted sums into the running
        accumulator. With ONE chunk this computes bit-for-bit
        tree_weighted_mean + _finish_round's loss: the deterministic
        streaming mode's bit-identity to batch aggregation is by
        construction, not by tolerance."""
        cohort_train = self._cohort_train

        def chunk_step(variables, acc, acc_w, acc_loss, cx, cy, cm, counts,
                       w_norm, rng):
            keys = jax.random.split(rng, cohort)[start:start + size]
            res = cohort_train(variables, cx, cy, cm, counts, keys)

            def wadd(a, x):
                wb = w_norm.reshape((-1,) + (1,) * (x.ndim - 1))
                return a + jnp.sum(x.astype(jnp.float32) * wb, axis=0)

            acc = jax.tree.map(wadd, acc, res.variables)
            w = counts.astype(jnp.float32)
            return (acc, acc_w + jnp.sum(w),
                    acc_loss + jnp.sum(res.train_loss * w))

        if not self.config.donate:
            return jax.jit(chunk_step)
        # donate the accumulator (replaced every chunk) and the chunk
        # buffers (this step is their last consumer) — chunked memory
        # stays flat instead of growing by in-flight chunks
        return _donation_quiet(jax.jit(chunk_step, donate_argnums=(1, 4, 5, 6)))

    def build_round_step_stream_packed(self, cohort: int, start: int,
                                       size: int, shape_key: tuple):
        """Packed-lanes variant of the streaming chunk step: the chunk's
        clients pack back-to-back into scan lanes
        (parallel/packed.make_packed_cohort_train over the materialized
        chunk arrays, key_slice preserving the canonical per-client keys),
        and the lane program's native weighted sums fold into the
        accumulator — the MXU fast path bounded by the accumulator, not by
        one program's cohort buffers."""
        from fedml_tpu.parallel.packed import (make_packed_cohort_train,
                                               resolve_packed_conv)

        c = self.config
        n_pad = int(self.dataset.train_x.shape[1])
        pconv = resolve_packed_conv(c.packed_conv, self.bundle,
                                    int(shape_key[0]),
                                    optimizer=c.client_optimizer)
        packed = make_packed_cohort_train(
            self.bundle, self.task, n_pad, shape_key,
            packed_conv=pconv, key_slice=(cohort, start),
            **self._local_train_kwargs())
        rows = jnp.arange(size, dtype=jnp.int32)

        def chunk_step(variables, acc, acc_w, acc_loss, cx, cy, cm, counts,
                       rng, plan_arrays):
            a, w, l, _tau, _extras = packed(
                variables, cx, cy, cm, rows, counts, rng, plan_arrays)
            acc = jax.tree.map(
                lambda s, p: s + p.astype(jnp.float32), acc, a)
            return acc, acc_w + w.astype(jnp.float32), \
                acc_loss + l.astype(jnp.float32)

        if not self.config.donate:
            return jax.jit(chunk_step)
        return _donation_quiet(jax.jit(chunk_step, donate_argnums=(1, 4, 5, 6)))

    def _stream_finish(self, packed: bool):
        """Round-close for the streaming fold: elastic all-failed rollback
        + weighted loss, mirroring _finish_round's arithmetic. The vmap
        fold accumulates normalize-first sums (the aggregate IS acc); the
        packed fold accumulates unnormalized lane sums (aggregate =
        acc / acc_w, the packed round's own tail)."""
        if self._stream_finish_fn is None:
            @jax.jit
            def finish_vmap(variables, acc, acc_w, acc_loss):
                keep = acc_w > 0
                new_vars = jax.tree.map(
                    lambda a, v: jnp.where(keep, a.astype(v.dtype), v),
                    acc, variables)
                return new_vars, acc_loss / jnp.maximum(acc_w, 1e-12)

            @jax.jit
            def finish_packed(variables, acc, acc_w, acc_loss):
                denom = jnp.maximum(acc_w, 1e-12)
                keep = acc_w > 0
                new_vars = jax.tree.map(
                    lambda a, v: jnp.where(keep, (a / denom).astype(v.dtype),
                                           v),
                    acc, variables)
                return new_vars, acc_loss / denom

            self._stream_finish_fn = (finish_vmap, finish_packed)
        return self._stream_finish_fn[1 if packed else 0]

    def _run_streaming_round(self, round_idx: int):
        """Execute one host round as streamed sub-cohort chunks: each chunk
        materializes (prefetched when the pipeline is on), trains, and
        folds into the running accumulator as it finishes on device —
        server memory is ONE f32 model sum regardless of cohort size."""
        c = self.config
        rk = round_key(self.root_key, round_idx)
        sampled, live, bucket = self._round_plan(round_idx, record=True)
        self._stash_plan(round_idx, sampled, live)
        spec = self._stream_chunk_spec(len(sampled))
        C = len(spec)
        cohort_n = len(sampled)
        packed = self._stream_packed_active()
        acc = jax.tree.map(lambda v: jnp.zeros(v.shape, jnp.float32),
                           self.variables)
        acc_w = jnp.zeros((), jnp.float32)
        acc_loss = jnp.zeros((), jnp.float32)
        pf = self._stream_prefetcher()
        mat_ms = h2d_ms = wait_ms = compute_ms = 0.0
        for ci, (start, size) in enumerate(spec):
            if pf is not None:
                (payload, meta), stages, w_ms = pf.pop(round_idx * C + ci)
                mat_ms += stages["materialize_ms"]
                h2d_ms += stages["h2d_ms"]
                wait_ms += w_ms
            else:
                t0 = time.perf_counter()
                payload, meta = self._stream_chunk_inputs(round_idx, ci)
                dt = (time.perf_counter() - t0) * 1e3
                mat_ms += dt
                wait_ms += dt    # serial: the host stage is fully exposed
            cx, cy, cm, counts, w_norm = payload
            t0 = time.perf_counter()
            if packed:
                from fedml_tpu.parallel.packed import (plan_arrays_tuple,
                                                       plan_packing)

                raw = self._counts_view(np.float64)[
                    sampled[start:start + size]]
                plan = plan_packing(
                    raw, c.batch_size, c.epochs, c.pack_lanes,
                    t_quantum=max(1, c.bucket_quantum_batches // 4))
                key = ("p", cohort_n, start, size, plan.shape_key)
                step = self._lru_step(
                    self._stream_steps, key,
                    lambda: self.build_round_step_stream_packed(
                        cohort_n, start, size, plan.shape_key),
                    "stream_step")
                acc, acc_w, acc_loss = step(
                    self.variables, acc, acc_w, acc_loss, cx, cy, cm,
                    jnp.asarray(counts), rk,
                    tuple(jnp.asarray(a)
                          for a in plan_arrays_tuple(plan)))
            else:
                key = ("v", cohort_n, start, size, meta[3])
                step = self._lru_step(
                    self._stream_steps, key,
                    lambda: self.build_round_step_stream_chunk(
                        cohort_n, start, size),
                    "stream_step")
                acc, acc_w, acc_loss = step(
                    self.variables, acc, acc_w, acc_loss, cx, cy, cm,
                    jnp.asarray(counts), jnp.asarray(w_norm), rk)
            compute_ms += (time.perf_counter() - t0) * 1e3
        self.variables, train_loss = self._stream_finish(packed)(
            self.variables, acc, acc_w, acc_loss)
        if not c.async_rounds:
            train_loss = float(train_loss)
        row = {"materialize_ms": mat_ms, "h2d_ms": h2d_ms,
               "wait_ms": wait_ms, "round": round_idx,
               "compute_ms": compute_ms}
        self._stage_rows.append(row)
        from fedml_tpu.obs import default_registry, tracer_if_sampled

        default_registry().append_row("stage", row)
        tr = tracer_if_sampled(0, round_idx)
        if tr is not None:
            tr.counter("host_stages", {
                k: row[k] for k in
                ("materialize_ms", "h2d_ms", "compute_ms", "wait_ms")},
                args={"round": round_idx})
        # the O(1)-memory evidence: the server-side round state is ONE f32
        # model-shaped accumulator + two scalars, independent of cohort
        self.stream_stats = {
            "mode": c.stream_aggregate, "cohort": cohort_n, "chunks": C,
            "chunk_clients": c.cohort_chunk if C > 1 else cohort_n,
            "packed_lanes": c.pack_lanes if packed else 0,
            "accumulator_bytes": int(sum(
                int(np.prod(v.shape)) * 4
                for v in jax.tree.leaves(self.variables)) + 8)}
        return train_loss

    def _traced_device_step(self, path: str, round_idx: int, step, *args):
        """Run one device round program under a ``mesh_step`` span so the
        trace can attribute the in-mesh device leg per round (the mesh
        counterpart of the edge paradigm's train leg). With async_rounds
        the span measures DISPATCH (+ trace/compile on a program's first
        call) — the tracer never forces a device sync."""
        from fedml_tpu.obs import tracer_if_sampled

        tr = tracer_if_sampled(0, round_idx)
        if tr is None:
            return step(*args)
        with tr.span("mesh_step", cat="device",
                     args={"round": round_idx, "path": path}):
            return step(*args)

    def close(self) -> None:
        """Drain and tear down background machinery (the host round
        pipeline). Idempotent; the API stays usable — the next host-path
        round lazily rebuilds the prefetcher."""
        pf = self._prefetcher
        self._prefetcher = None
        if pf is not None:
            pf.close()
        spf = self._stream_pf
        self._stream_pf = None
        if spf is not None:
            spf.close()

    # -- driver --------------------------------------------------------------

    def run_round(self, round_idx: int) -> "float | jax.Array":
        """Execute one round; returns the weighted train loss — a host float,
        or (config.async_rounds) the un-synced device scalar so consecutive
        rounds pipeline; callers that do host arithmetic must float() it.

        THE traced wrapper: every paradigm's round logic lives in
        ``_run_round_inner`` (subclasses override THAT, never this — the
        fedlint ``trace-coverage`` rule enforces it), so one span per round
        plus the round-boundary device-memory sample cover the whole zoo.
        The fedpulse plane rides the same wrapper: with ``--pulse_path``
        set, every round feeds the per-client profiler and appends one
        snapshot to the pulse stream — both gates are one global read when
        off, and neither touches the round's math. Under
        ``--trace_sample_rate`` the tracer gate is the deterministic
        head-sampling verdict for THIS round: a sampled-out round emits no
        spans, but the pulse/sketch feed below still sees it."""
        from fedml_tpu.obs import (pulse_if_enabled, sample_device_memory,
                                   tracer_if_sampled)

        tr = tracer_if_sampled(0, round_idx)
        pulse = pulse_if_enabled()
        sched = self._cohort_sched
        if tr is None and pulse is None:
            out = self._run_round_inner(round_idx)
            if sched.wants_notify:
                sched.notify_round_done(round_idx)
            return out
        t0 = time.perf_counter()
        if tr is None:
            out = self._run_round_inner(round_idx)
        else:
            with tr.span("round", cat="round", args={"round": round_idx}):
                out = self._run_round_inner(round_idx)
            if getattr(self.config, "trace_device_sampler", True):
                sample_device_memory(tr, round_idx)
        if pulse is not None:
            # with async_rounds `out` is an un-synced device scalar and the
            # wall measured dispatch; the plane never float()s it (that
            # would force the sync the flag exists to avoid)
            pulse.on_sim_round(self, round_idx,
                               out, (time.perf_counter() - t0) * 1e3)
        # fedsched boundary: snapshot the profiler AFTER this round's pulse
        # feed, so the plan for round r + SCHED_LAG sees it
        if sched.wants_notify:
            sched.notify_round_done(round_idx)
        return out

    def set_cohort_profiler(self, source) -> None:
        """Freeze the fedsched scheduling signal to ``source`` (a
        ClientProfiler or ProfileSnapshot; None clears): every plan then
        derives from this one snapshot — timing- and pipeline-depth-
        independent, the determinism mode tools/xdev_ab.py --policy pins."""
        self._cohort_sched.set_static_profile(source)

    def _stash_plan(self, round_idx: int, sampled, live) -> None:
        """Record a computed round plan for :meth:`_pulse_cohort` (single
        dict store under the GIL — the prefetcher's background builds and
        the main thread may both write, always to distinct round keys)."""
        stash = self._plan_stash
        stash[int(round_idx)] = (sampled, live)
        while len(stash) > 16:   # bound: pipeline depth + slack
            stash.pop(next(iter(stash)))

    def _pulse_cohort(self, round_idx: int) -> Optional[np.ndarray]:
        """Logical client ids this round actually TRAINED, for the fedpulse
        profiler. Default: the round plan's live cohort, reusing the plan
        the round (or its background prefetch build) already stashed —
        the fallback re-derivation is deterministic but re-pays the
        O(client_num_in_total) sampling draw. Paradigms whose rounds
        train a different population than the sampled cohort (the
        decentralized gossip family trains EVERY node) override this —
        otherwise the pulse stream would profile a phantom cohort."""
        plan = self._plan_stash.pop(int(round_idx), None)
        if plan is not None:
            sampled, live = plan
        else:
            sampled, live, _bucket = self._round_plan(round_idx)
        ids = np.asarray(sampled, np.int64)
        if live is not None:
            ids = ids[np.asarray(live) > 0]
        return ids

    # -- fedlens (obs/lens.py) ----------------------------------------------

    #: class-level defaults so subclasses need no __init__ surgery; the
    #: armed state is snapshotted at the FIRST armed-check (i.e. the first
    #: round program trace), mirroring the tracer's arm-before-build rule
    _lens_state: "Optional[bool]" = None
    _lens_stash = None
    _lens_prev = None

    @property
    def _lens_armed(self) -> bool:
        on = self._lens_state
        if on is None:
            from fedml_tpu.obs.lens import lens_enabled

            # one-time snapshot BY DESIGN: the armed bit is frozen at the
            # first round-program trace so lens on/off can never re-trace
            # mid-run (the trace-time-only behavior the rule warns about
            # is exactly the contract)  # fedlint: disable=traced-purity
            on = self._lens_state = bool(lens_enabled())
        return on

    def _lens_absorb(self, round_idx: int, out, ids, valid=None):
        """Strip + stash the lens element when an armed round program
        returned one (device arrays stay un-synced); 3-tuples pass
        through. ``ids`` are the logical client ids in the lens arrays'
        stacking order; ``valid`` masks padding/failed entries."""
        if len(out) == 4:
            self._lens_stash = (
                int(round_idx), np.asarray(ids, np.int64),
                None if valid is None else np.asarray(valid, bool), out[3])
            out = out[:3]
        return out

    def _pulse_lens(self, round_idx: int):
        """The round's lens stats as host arrays for the pulse feed —
        ``(round, ids, {"update_norm", "align"[, "loss_delta"]})`` or
        None. Under ``--async_rounds`` conversion runs one round LATE (the
        previous round's arrays are already materialized), so the feed
        never forces a host sync on the round just dispatched; ids ride
        with their stats, so the one-round lag cannot misattribute."""
        cur, self._lens_stash = self._lens_stash, None
        if self.config.async_rounds:
            cur, self._lens_prev = self._lens_prev, cur
        if cur is None:
            return None
        r, ids, valid, dev = cur
        stats = {k: np.asarray(v, np.float64) for k, v in dev.items()}
        if valid is not None:
            ids = ids[valid]
            stats = {k: v[valid] for k, v in stats.items()}
        if ids.size == 0:
            return None
        return r, ids, stats

    def _pulse_cohort_shares(self, ids) -> "Optional[np.ndarray]":
        """Per-client share of the round wall for the fedpulse profiler
        feed: proportional to each client's record count — within a fused
        cohort a client with 3x the records consumed ~3x the materialize +
        compute, so count-weighted attribution is the honest amortization
        (and the signal that lets the ``speed`` policy tell a heavy client
        from a light one). None = even split (paradigms whose cohorts
        don't map to the stacked count table override _pulse_cohort and
        may not have counts for every id)."""
        counts = self._counts_view(np.float64)
        ids = np.asarray(ids, np.int64)
        if ids.size == 0 or ids.max(initial=-1) >= counts.shape[0]:
            return None
        c = counts[ids]
        total = float(c.sum())
        return c / total if total > 0 else None

    def _run_round_inner(self, round_idx: int) -> "float | jax.Array":
        rk = round_key(self.root_key, round_idx)
        if self._dev_train is not None:
            sampled, live, bucket = self._round_plan(round_idx, record=True)
            self._stash_plan(round_idx, sampled, live)
            live_np = (np.ones((len(sampled),), np.float32) if live is None
                       else np.asarray(live, np.float32))
            if self.config.pack_lanes > 0:
                out = self._run_packed_round(sampled, live, rk, round_idx)
                if out is not None:
                    self.variables, self.server_state, train_loss = out
                    return (train_loss if self.config.async_rounds
                            else float(train_loss))
            plan = self._round_groups(sampled, live)
            if plan is not None:
                perm, groups = plan
                step = self._lru_step(
                    self._group_steps, groups,
                    lambda: self.build_round_step_gather_groups(groups),
                    "group_step")
                out = step(
                    self.variables, self.server_state, *self._dev_train,
                    jnp.asarray(sampled[perm], jnp.int32),
                    jnp.asarray(live_np[perm]),
                    jnp.asarray(perm, jnp.int32), rk
                )
                self.variables, self.server_state, train_loss = \
                    self._lens_absorb(round_idx, out,
                                      np.asarray(sampled, np.int64)[perm],
                                      live_np[perm] > 0)
                return train_loss if self.config.async_rounds else float(train_loss)
            if bucket is None:
                step = self._round_step_gather
            else:
                step = self._lru_step(
                    self._gather_steps, bucket,
                    lambda: self.build_round_step_gather(bucket),
                    "gather_step")
            out = step(
                self.variables, self.server_state, *self._dev_train,
                jnp.asarray(sampled, jnp.int32), jnp.asarray(live_np), rk
            )
            self.variables, self.server_state, train_loss = \
                self._lens_absorb(round_idx, out, sampled, live_np > 0)
        else:
            if self._stream_mode() != "off":
                # fedsched streaming path: sub-cohort chunks fold into the
                # running accumulator as they finish (O(1) server memory
                # in cohort size); unchunked deterministic mode computes
                # the batch program's arithmetic bit-for-bit
                return self._run_streaming_round(round_idx)
            pf = self._host_prefetcher()
            if pf is not None:
                # pipelined: the background build computes the full plan
                # itself, so only the record=True side effects (failure
                # history + log) run here — NOT the O(client_num_in_total)
                # sampling draw, which would sit on the critical path this
                # pipeline exists to clear
                self._sample_failures(
                    round_idx,
                    min(self.config.client_num_per_round,
                        self.dataset.num_clients), record=True)
                (cx, cy, cm, counts), stages, wait_ms = pf.pop(round_idx)
                step = self._host_pipeline_step()
            else:
                t0 = time.perf_counter()
                sampled, live, bucket = self._round_plan(round_idx, record=True)
                self._stash_plan(round_idx, sampled, live)
                cx, cy, cm, counts = self._host_round_inputs(
                    round_idx, plan=(sampled, live, bucket))
                mat_ms = (time.perf_counter() - t0) * 1e3
                # serial: the host stages are fully exposed (wait == work)
                stages, wait_ms = {"materialize_ms": mat_ms, "h2d_ms": 0.0}, mat_ms
                step = self._round_step
            t0 = time.perf_counter()
            out = step(
                self.variables, self.server_state, cx, cy, cm,
                jnp.asarray(counts, jnp.float32), rk
            )
            if len(out) == 4:
                # host-path cohort order is the stashed plan's sampled
                # order; the prefetcher stashes its plans too, so the id
                # mapping survives pipelining (absent plan = lens skipped)
                plan_s = self._plan_stash.get(int(round_idx))
                if plan_s is not None:
                    s_ids, s_live = plan_s
                    out = self._lens_absorb(
                        round_idx, out, s_ids,
                        None if s_live is None else np.asarray(s_live) > 0)
                else:
                    out = out[:3]
            self.variables, self.server_state, train_loss = out
            if not self.config.async_rounds:
                train_loss = float(train_loss)
            row = dict(stages, wait_ms=wait_ms, round=round_idx,
                       compute_ms=(time.perf_counter() - t0) * 1e3)
            self._stage_rows.append(row)
            from fedml_tpu.obs import default_registry, tracer_if_sampled

            # the registry's stage-row record mirrors _stage_rows (the
            # round_stats view) so registry readers (MetricsLogger,
            # tests) see the same numbers the summary reports; the trace
            # analyzer gets its copy via the host_stages counter below
            default_registry().append_row("stage", row)
            tr = tracer_if_sampled(0, round_idx)
            if tr is not None:
                tr.counter("host_stages", {
                    k: row[k] for k in
                    ("materialize_ms", "h2d_ms", "compute_ms", "wait_ms")},
                    args={"round": round_idx})
        return train_loss if self.config.async_rounds else float(train_loss)

    def save(self, path: str, round_idx: int = 0, orbax: bool = False) -> None:
        """Checkpoint variables + server state (+ resume round). The
        reference cannot do this at all (SURVEY.md §5.4: duck-typed
        save_model, no resume); ``orbax=True`` writes a sharded checkpoint."""
        from fedml_tpu.utils import checkpoint as ckpt

        if orbax:
            ckpt.save_checkpoint_orbax(path, self.variables, self.server_state, round_idx)
        else:
            ckpt.save_checkpoint(path, jax.tree.map(np.asarray, self.variables),
                                 jax.tree.map(np.asarray, self.server_state),
                                 round_idx)

    def restore(self, path: str, orbax: bool = False) -> int:
        """Load a checkpoint into this API; returns the round index to
        resume from. Training continued from here is identical to an
        uninterrupted run (per-round RNG is derived from round_idx)."""
        from fedml_tpu.utils import checkpoint as ckpt

        if orbax:
            # the live state is the restore template: orbax rebuilds optax
            # namedtuples (and shardings) only when given the matching pytree
            state = ckpt.load_checkpoint_orbax(
                path, template={"variables": self.variables,
                                "server_state": self.server_state})
        else:
            state = ckpt.load_checkpoint(path)
        self.variables = jax.tree.map(jnp.asarray, state["variables"])
        self.server_state = jax.tree.map(jnp.asarray, state["server_state"])
        return int(state["round_idx"])

    def evaluate_global(self) -> dict:
        variables = self.variables
        if jax.process_count() > 1:
            # round outputs are replicated over the multi-process mesh;
            # eval is process-local, so pull the (fully-replicated) host
            # view first — mixing global and local arrays in one jit is
            # not a valid multi-process program
            variables = jax.tree.map(np.asarray, variables)
        sums = self._eval(
            variables, self.dataset.test_x, self.dataset.test_y, self.dataset.test_mask
        )
        return finalize_metrics(jax.tree.map(np.asarray, sums))

    def train(self) -> dict:
        from fedml_tpu.obs import (configure_from, default_registry,
                                   flush_all, tracing_enabled)
        from fedml_tpu.utils.metrics import MetricsLogger, RoundTimer, profile_trace

        c = self.config
        configure_from(c)
        # the registry row store is process-wide; start this run's stage
        # record clean so readers don't see earlier runs' rounds interleaved
        default_registry().clear_rows("stage")
        timer = RoundTimer()
        logger = MetricsLogger(c.run_name, c.enable_wandb, config=c.to_dict())
        start_round = 0
        if c.resume_from:
            start_round = self.restore(c.resume_from)
            log.info("resumed from %s at round %d", c.resume_from, start_round)
        try:
            with profile_trace(c.profile_dir):
                self._train_rounds(start_round, timer, logger)
        finally:
            # drain the host round pipeline: no background thread may
            # outlive the run (speculative builds are dropped harmlessly —
            # every payload is a pure function of round_idx)
            self.close()
            if tracing_enabled():
                flush_all()
        timing = timer.summary()
        if self._stage_rows:
            from fedml_tpu.utils.metrics import round_stats

            timing["host_pipeline"] = round_stats(
                self._stage_rows, c.host_pipeline_depth)
        if c.async_rounds:
            # run_round returned un-synced device scalars, so the 'train'
            # phase timed DISPATCH only; only eval rounds (float(loss)) and
            # the final eval actually blocked. Wall-clock — and
            # rounds_per_sec, which divides by it — still ends on a real
            # sync, so those stay honest.
            timing["time/train_is_dispatch_only"] = True
        self.history["rounds_per_sec"] = timing["rounds_per_sec"]
        self.history["timing"] = timing
        self.metrics_logger = logger
        logger.close()
        return self.history

    def _eval_at(self, r: int) -> bool:
        """Whether to run the periodic eval after round ``r`` (self.variables
        holds the post-round-r model at that point). Subclasses whose
        run_round advances state in blocks (super-step) override this to
        align evals to block ends."""
        c = self.config
        return r % c.frequency_of_the_test == 0 or r == c.comm_round - 1

    def _train_rounds(self, start_round, timer, logger):
        c = self.config
        for r in range(start_round, c.comm_round):
            with timer.phase("train"):
                loss = self.run_round(r)
            timer.tick_round()
            if self._eval_at(r):
                with timer.phase("eval"):
                    m = self.evaluate_global()
                self.history["round"].append(r)
                self.history["Test/Acc"].append(m.get("acc"))
                self.history["Test/Loss"].append(m.get("loss"))
                logger.log(
                    {"Train/Loss": float(loss), "Test/Acc": m.get("acc"),
                     "Test/Loss": m.get("loss")}, r,
                )
            if c.checkpoint_dir and (
                (r + 1) % c.checkpoint_frequency == 0 or r == c.comm_round - 1
            ):
                import os

                self.save(os.path.join(c.checkpoint_dir, "latest.ckpt"), r + 1)


class CrossSiloFedAvgAPI(FedAvgAPI):
    """Cross-silo distributed paradigm: clients sharded over a device mesh,
    aggregation = weighted psum on ICI (replaces the reference's MPI
    ServerManager/ClientManager star, SURVEY.md §3.2).

    The sampled cohort size must be a multiple of the mesh size; each device
    trains cohort/mesh_size clients per round under vmap.
    """

    supports_device_data = False  # base gather path replaced by _dev_sharded
    handles_own_device_data = True  # _maybe_place_sharded honors the flag
    elastic_rounds_ok = True      # the psum path guards zero total weight

    def __init__(self, dataset, config, bundle=None, mesh=None, **kw):
        from fedml_tpu.parallel.mesh import client_mesh

        self.mesh = mesh or client_mesh()
        super().__init__(dataset, config, bundle, **kw)
        axis_sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        if "clients" not in axis_sizes:
            raise ValueError(f"mesh must have a 'clients' axis, got {self.mesh.axis_names}")
        n_clients_axis = axis_sizes["clients"]
        # The EFFECTIVE cohort (run_round clamps to the dataset's client count)
        # is what gets sharded — validate that, not the raw config value.
        cohort = min(config.client_num_per_round, dataset.num_clients)
        if cohort % n_clients_axis:
            raise ValueError(
                f"effective cohort size ({cohort}) must be a multiple of the "
                f"mesh 'clients' axis ({n_clients_axis})"
            )
        if config.cohort_vmap_width > 0:
            # the mesh round programs vmap each device's client block inside
            # shard_map; the chunked schedule applies to the simulation
            # paradigm only (and measured FLAT there — mfu_experiments H4)
            log.warning(
                "cohort_vmap_width=%d ignored: the cross-silo mesh round "
                "always vmaps the per-device client block",
                config.cohort_vmap_width)
        self._dev_sharded = self._dev_groups = self._group_plan = None
        self._packed_mesh = None
        if config.pack_lanes > 0:
            self._packed_mesh = self._mesh_packed_setup(cohort)
        if self._packed_mesh is None:
            plan = self._mesh_group_plan(cohort)
            if plan is not None:
                self._dev_groups = self._place_grouped(plan)
                if self._dev_groups is not None:
                    self._group_plan = plan
                    self._grouped_step = self.build_round_step_grouped(len(plan))
            if self._dev_groups is None:
                self._dev_sharded = self._maybe_place_sharded(cohort)

    def _mesh_packed_setup(self, cohort: int):
        """Resident placement + program for the packed mesh schedule
        (parallel/packed.py): per-device lanes, one psum tail. Returns None
        when packing doesn't apply (falls back to grouped/sharded)."""
        from fedml_tpu.parallel.packed import (
            impl_label,
            make_crosssilo_packed_round,
            packed_conv_active,
            plan_packing_mesh,
            resolve_packed_conv,
        )

        c, ds = self.config, self.dataset
        # ONE packability gate for both paradigms (_packing_hooks): the
        # mesh and sim packed paths must agree on which algorithms mirror
        # onto the lanes — a condition added to one must gate the other
        hooks = self._packing_hooks()
        if hooks is None:
            return None
        if cohort != ds.num_clients:
            log.warning(
                "pack_lanes=%d ignored on the mesh path: the packed "
                "schedule is resident-sharded and needs full participation "
                "(cohort %d != clients %d)", c.pack_lanes, cohort,
                ds.num_clients)
            return None
        D = self.mesh.shape["clients"]
        lanes_dev = max(1, -(-c.pack_lanes // D))
        # full participation -> ONE static plan, compiled once: no reason to
        # quantize the lane length at all
        out = plan_packing_mesh(
            np.asarray(ds.train_counts), c.batch_size, c.epochs, D, lanes_dev,
            t_quantum=1)
        if out is None:
            return None
        perm, plan = out
        x = self._eligible_device_train_x(shard_factor=D)
        if x is None:
            return None
        from fedml_tpu.parallel.mesh import shard_client_batch

        n_pad = int(ds.train_x.shape[1])
        from fedml_tpu.parallel.packed import plan_arrays_tuple

        data = shard_client_batch(self.mesh, (
            x[perm], np.asarray(ds.train_y)[perm],
            np.asarray(ds.train_mask)[perm]))
        plan_arrays = shard_client_batch(self.mesh, plan_arrays_tuple(plan))
        from fedml_tpu.obs import timed_build

        # fedscope compile telemetry: the packed mesh program is the most
        # expensive build in the tree (shard_map over vmapped lanes); its
        # shape key is the lane geometry that determines the XLA program
        # fedplan: resolve 'auto' against the PER-DEVICE lane count — the
        # contraction each device runs folds plan.n_lanes // D clients
        pconv = resolve_packed_conv(c.packed_conv, self.bundle,
                                    int(plan.n_lanes // D),
                                    optimizer=c.client_optimizer)

        def _build():
            rf = make_crosssilo_packed_round(
                self.bundle, self.task, n_pad, self.mesh,
                packed_conv=pconv, **hooks,
                **self._local_train_kwargs())
            # fedcost packing hint: the per-DEVICE contraction folds
            # lanes_dev clients (obs/cost.attribute_program)
            active = packed_conv_active(self.bundle, pconv,
                                        c.client_optimizer)
            rf.cost_hints = {
                "packed_conv": impl_label(pconv) if active else "off",
                "packing_factor": int(plan.n_lanes // D)}
            if active and not isinstance(pconv, str):
                rf.cost_hints["plan"] = pconv
            return rf

        round_fn = timed_build(
            "mesh_packed_round",
            (n_pad, D, lanes_dev, plan.shape_key, c.packed_conv), _build)
        return dict(perm=perm, plan=plan, data=data, plan_arrays=plan_arrays,
                    counts_perm=np.asarray(ds.train_counts, np.float32)[perm],
                    round_fn=round_fn)

    def _maybe_place_sharded(self, cohort: int):
        """Full-participation cross-silo (the standard silo deployment:
        every silo trains every round) keeps the whole dataset RESIDENT and
        SHARDED over the mesh — each device holds its clients' records in
        its own HBM, so rounds have zero host->device data movement (the
        in-mesh analogue of the simulation paradigm's device_data gather).
        Partial participation keeps the per-round host slice (a gather
        across shards would move data anyway)."""
        c = self.config
        ds = self.dataset
        if c.device_data == "off":
            return None
        if cohort != ds.num_clients:
            if c.device_data == "on":
                log.warning(
                    "device_data='on' ignored for cross-silo partial "
                    "participation (%d/%d clients); resident sharding needs "
                    "full participation", cohort, ds.num_clients)
            return None
        x = self._eligible_device_train_x(shard_factor=self.mesh.shape["clients"])
        if x is None:
            return None
        from fedml_tpu.parallel.mesh import shard_client_batch

        return shard_client_batch(
            self.mesh,
            (x, ds.train_y, ds.train_mask,
             np.asarray(ds.train_counts, np.float32)),
        )

    def _mesh_group_plan(self, cohort: int):
        """Static grouped schedule for the resident-sharded full-participation
        path — the mesh form of ``_round_groups``. Count-sorted clients are
        dealt to devices in STRIPS (strip s = clients [sD, (s+1)D), one per
        device), so strip scan lengths are global constants and the SPMD
        program is identical on every device; consecutive strips are chunked
        into at most ``bucket_groups`` groups whose scan length is the chunk's
        quantum-rounded max count. Returns None (schedule off / nothing to
        trim) or a tuple of (idx_g, scan_len_g): ``idx_g`` lists the group's
        client indices DEVICE-MAJOR (shard d of the stacked group axis =
        that device's strip slots)."""
        c = self.config
        ds = self.dataset
        if c.device_data == "off" or cohort != ds.num_clients:
            return None
        D = self.mesh.shape["clients"]
        L = ds.num_clients // D           # clients per device
        if c.bucket_groups <= 1 or L < 2:
            return None
        n_pad = int(ds.train_x.shape[1])
        q = c.bucket_quantum_batches * c.batch_size
        if c.bucket_quantum_batches <= 0 or q >= n_pad:
            return None
        counts = np.asarray(ds.train_counts, np.float64)
        strips = np.argsort(counts, kind="stable").reshape(L, D)
        strip_max = counts[strips].max(axis=1)      # nondecreasing
        merged = _chunk_buckets(strip_max, min(c.bucket_groups, L), q, n_pad)
        if len(merged) == 1 and merged[0][2] >= n_pad:
            return None                             # nothing to trim
        return tuple((strips[a:b].T.reshape(-1), bucket) for a, b, bucket in merged)

    def _place_grouped(self, plan):
        """Resident placement for the grouped schedule: per group, the
        stacked client arrays are gathered in plan order, TRUNCATED to the
        group's scan length on host (saving the HBM the padding tail would
        occupy), and sharded over the mesh. Returns (groups, counts) tuples
        or None when the dataset is ineligible for residency."""
        ds = self.dataset
        n_slots = ds.num_clients * int(ds.train_x.shape[1])
        kept = sum(len(idx_g) * bucket for idx_g, bucket in plan)
        x = self._eligible_device_train_x(
            shard_factor=self.mesh.shape["clients"],
            slots_fraction=kept / max(n_slots, 1))
        if x is None:
            return None
        from fedml_tpu.parallel.mesh import shard_client_batch

        groups, counts = [], []
        for idx_g, bucket in plan:
            # single-step fancy index: produce ONLY the truncated copy
            # (x[idx_g][:, :bucket] would materialize full padded rows first)
            gx = x[idx_g, :bucket]
            gy = np.asarray(ds.train_y)[idx_g, :bucket]
            gm = np.asarray(ds.train_mask)[idx_g, :bucket]
            placed = shard_client_batch(self.mesh, (
                gx, gy, gm, np.asarray(ds.train_counts, np.float32)[idx_g]))
            groups.append(placed[:3])
            counts.append(placed[3])
        return tuple(groups), tuple(counts)

    def build_round_step_grouped(self, n_groups: int):
        from fedml_tpu.parallel.crosssilo import make_crosssilo_round_grouped
        from fedml_tpu.parallel.mesh import client_sharded, global_put, replicated

        round_fn = make_crosssilo_round_grouped(
            self._local_train, self.mesh, n_groups,
            **self._crosssilo_hooks_checked())
        rep, sh = replicated(self.mesh), client_sharded(self.mesh)

        def round_step(variables, server_state, groups, counts, rng):
            # every client keeps the per-round key of its ORIGINAL index, so
            # the grouped schedule changes only the padding steps a client
            # burns, never which randomness it consumes
            keys_full = jax.random.split(rng, self.dataset.num_clients)
            if jax.process_count() == 1:   # device-side gather (hot path)
                keys = tuple(jax.device_put(keys_full[idx_g], sh)
                             for idx_g, _ in self._group_plan)
            else:                          # global_put handles typed keys
                keys = tuple(global_put(keys_full[idx_g], sh)
                             for idx_g, _ in self._group_plan)
            variables = global_put(variables, rep)
            server_state = global_put(server_state, rep)
            return round_fn(variables, server_state, groups, counts, keys,
                            global_put(rng, rep))

        return round_step

    def _superstep_h(self) -> int:
        """Effective super-step length: disabled (1) when checkpointing
        would land MID-block — inside a block self.variables holds the
        block-end state, so a mid-block checkpoint would double-apply
        rounds on resume (review r5). Periodic evals no longer disable the
        super-step: _eval_at aligns them to block ends with true round
        labels (ADVICE r5 medium — the old block-START guard reported the
        post-block model under the start round's label, shifting
        convergence curves by h-1 rounds)."""
        h = self.config.rounds_per_step
        if h <= 1:
            return 1
        c = self.config
        if getattr(c, "checkpoint_dir", None) or getattr(c, "resume_from", None):
            if not getattr(self, "_warned_ss", False):
                log.warning("rounds_per_step=%d ignored: checkpointing "
                            "needs per-round state", h)
                self._warned_ss = True
            return 1
        return h

    def _eval_at(self, r: int) -> bool:
        """Super-step blocks advance self.variables to the BLOCK-END state
        on the block's first round, so evals only run at block ends — at
        which point self.variables is exactly the post-round-r model — and
        a block end evals iff its block contains a round the plain-path
        schedule would have evaluated (or it is the final round)."""
        h = self._superstep_h()
        if h <= 1 or self._packed_mesh is None:
            return super()._eval_at(r)
        c = self.config
        if c.failure_prob:
            # failure injection forces run_round onto the per-round path
            # (live mask every round), so variables ARE post-round-r state
            # at every r — keep the plain eval schedule
            return super()._eval_at(r)
        if r == c.comm_round - 1:
            return True
        base = getattr(self, "_ss_base", 0)
        if (r - base + 1) % h != 0:
            return False               # mid-block: variables are from the future
        start = r - h + 1
        return any(k % c.frequency_of_the_test == 0 for k in range(start, r + 1))

    def _packed_superstep_fn(self, h: int):
        """One jitted program running ``h`` packed rounds as a lax.scan over
        round keys — the fixed per-round cost (dispatch, program prologue,
        aggregation tail serialization) is paid once per h rounds instead of
        every round (the weak-scaling intercept lever, docs/perf.md)."""
        pm = self._packed_mesh
        # scan the RAW round body: scanning the jitted wrapper drags the
        # loop-invariant resident data into the while carry (per-iteration
        # full-tensor copies — measured 14-28x slower on the chip)
        inner = pm["round_fn"].raw

        @jax.jit
        def super_fn(variables, server_state, tx, ty, tm, w_dev, perm, rks,
                     plan_arrays):
            def body(carry, rk):
                v, s = carry
                v, s, loss = inner(v, s, tx, ty, tm, w_dev, perm, rk,
                                   plan_arrays)
                return (v, s), loss

            # unroll=h: the rolled while-form measured ~4x slower per
            # iteration than the standalone round (CPU and TPU both)
            # despite identical per-iteration cost-model flops — unrolling
            # keeps the one-dispatch amortization without while mechanics
            (v, s), losses = jax.lax.scan(body, (variables, server_state),
                                          rks, unroll=h)
            return v, s, losses

        hints = getattr(pm["round_fn"], "cost_hints", None)
        if hints is not None:
            super_fn.cost_hints = hints  # fedpack: same packed GEMMs x h
        return super_fn

    def _run_superstep(self, start: int, blk: int, w):
        """Compute one super-step block and cache its per-round losses.

        Trace semantics (DESIGN.md §12): the block is ONE device program, so
        it emits ONE ``superstep`` span annotated with its covered round
        range, plus ``blk`` amortized ``mesh_round`` child spans (each
        dur/blk, evenly placed) so per-round views of the timeline still
        decompose — amortized attribution, flagged as such, because the scan
        gives the tracer no real per-round boundary to observe. Under
        ``--trace_sample_rate`` the sampling unit is the whole BLOCK, keyed
        by its starting round (the block is one program — per-round gating
        inside it would tear the amortized children from their parent): a
        sampled-out block emits nothing, so span volume stays bounded on
        the superstep path too."""
        from fedml_tpu.obs import timed_build, tracer_if_sampled
        from fedml_tpu.parallel.mesh import shard_client_batch

        pm = self._packed_mesh
        fns = getattr(self, "_ss_fns", None)
        if fns is None:
            fns = self._ss_fns = {}
        if blk not in fns:
            fns[blk] = timed_build("superstep_fn", (blk,),
                                   lambda: self._packed_superstep_fn(blk))
        rks = jnp.stack([round_key(self.root_key, start + i)
                         for i in range(blk)])
        (w_dev,) = shard_client_batch(self.mesh, (w,))
        # client-active exits ride the superstep too: masked w (caller) +
        # masked plan arrays, picked up at each block START — a mid-block
        # mask change takes effect at the next block boundary (the block
        # is one device program; see set_client_active)
        step_args = (self.variables, self.server_state, *pm["data"], w_dev,
                     jnp.asarray(pm["perm"], jnp.int32), rks,
                     self._mesh_plan_arrays())
        tr = tracer_if_sampled(0, start)
        if tr is None:
            out = fns[blk](*step_args)
        else:
            ts0 = time.time_ns() // 1_000
            t0 = time.perf_counter()
            with tr.span("superstep", cat="device",
                         args={"round_start": start,
                               "round_end": start + blk - 1, "h": blk,
                               "path": "packed_mesh"}) as sp:
                out = fns[blk](*step_args)
            slice_us = max(int((time.perf_counter() - t0) * 1e6) // blk, 1)
            for i in range(blk):
                tr.emit_complete(
                    "mesh_round", cat="device",
                    ts_us=ts0 + i * slice_us, dur_us=slice_us,
                    parent_id=sp.span_id,
                    args={"round": start + i, "amortized": True,
                          "path": "packed_mesh",
                          "superstep": [start, start + blk - 1]})
        self.variables, self.server_state, losses = out
        return losses

    def _mesh_plan_arrays(self):
        """The packed-mesh plan arrays, with the Silo client-active mask
        applied as a STRUCTURAL lane freeze (mask_plan_arrays) when set —
        re-placed over the mesh once per mask version, so exits cost one
        host->device plan upload, never a recompile (shapes unchanged)."""
        pm = self._packed_mesh
        if self._client_active is None:
            return pm["plan_arrays"]
        cached = getattr(self, "_masked_mesh_plan", None)
        if cached is not None and cached[0] == self._client_active_version:
            return cached[1]
        from fedml_tpu.parallel.mesh import shard_client_batch
        from fedml_tpu.parallel.packed import (mask_plan_arrays,
                                               mesh_member_active)

        ma = mesh_member_active(
            pm["plan"], self.mesh.shape["clients"],
            np.asarray(self._client_active, np.float32)[pm["perm"]])
        placed = shard_client_batch(self.mesh,
                                    mask_plan_arrays(pm["plan"], ma))
        self._masked_mesh_plan = (self._client_active_version, placed)
        return placed

    def _run_round_inner(self, round_idx: int) -> float:
        if self._packed_mesh is not None:
            from fedml_tpu.parallel.mesh import shard_client_batch

            pm = self._packed_mesh
            live = self._sample_failures(round_idx, self.dataset.num_clients)
            w = pm["counts_perm"]
            if self._client_active is not None:
                # weight-zero exits everywhere; the packed program also gets
                # the structural lane freeze via _mesh_plan_arrays
                w = w * np.asarray(self._client_active, np.float32)[pm["perm"]]
            h = self._superstep_h()
            if h > 1 and live is None:
                # super-step block: round_idx falls in block
                # [start, start+h); compute the whole block once, hand out
                # the cached per-round device losses. A block's FIRST round
                # always recomputes, so re-running the same rounds (the
                # bench's warm+timed passes) re-executes like the plain path.
                if not hasattr(self, "_ss_base"):
                    self._ss_base = round_idx
                start = ((round_idx - self._ss_base) // h) * h + self._ss_base
                # the tail block is clamped so the scan NEVER trains rounds
                # past the federation's total (review r5: comm_round % h)
                done_before = start - self._ss_base
                blk = min(h, self.config.comm_round - done_before)
                cached = getattr(self, "_ss_cache", None)
                if cached is None or cached[0] != start or round_idx == start:
                    losses = self._run_superstep(start, blk, w)
                    self._ss_cache = cached = (start, losses)
                train_loss = cached[1][round_idx - start]
                return (train_loss if self.config.async_rounds
                        else float(train_loss))
            if live is not None:
                w = w * np.asarray(live, np.float32)[pm["perm"]]
            rk = round_key(self.root_key, round_idx)
            (w_dev,) = shard_client_batch(self.mesh, (w,))
            self.variables, self.server_state, train_loss = \
                self._traced_device_step(
                    "packed_mesh", round_idx, pm["round_fn"],
                    self.variables, self.server_state, *pm["data"], w_dev,
                    jnp.asarray(pm["perm"], jnp.int32), rk,
                    self._mesh_plan_arrays())
            return train_loss if self.config.async_rounds else float(train_loss)
        if self._dev_groups is not None:
            groups, counts_res = self._dev_groups
            live = self._sample_failures(round_idx, self.dataset.num_clients)
            if self._client_active is not None:
                live = (self._client_active if live is None
                        else live * self._client_active)
            if live is not None:
                counts = tuple(
                    c * jnp.asarray(live[idx_g], jnp.float32)
                    for c, (idx_g, _) in zip(counts_res, self._group_plan))
            else:
                counts = counts_res
            rk = round_key(self.root_key, round_idx)
            self.variables, self.server_state, train_loss = \
                self._traced_device_step(
                    "grouped", round_idx, self._grouped_step,
                    self.variables, self.server_state, groups, counts, rk)
            return train_loss if self.config.async_rounds else float(train_loss)
        if self._dev_sharded is None:
            return super()._run_round_inner(round_idx)
        cx, cy, cm, counts = self._dev_sharded
        live = self._sample_failures(round_idx, self.dataset.num_clients)
        if self._client_active is not None:
            live = (self._client_active if live is None
                    else live * self._client_active)
        if live is not None:
            counts = counts * jnp.asarray(live, jnp.float32)
        rk = round_key(self.root_key, round_idx)
        out = self._traced_device_step(
            "sharded", round_idx, self._round_step,
            self.variables, self.server_state, cx, cy, cm, counts, rk)
        # fedlens (plain mesh): full participation in dataset order, so the
        # logical ids are simply arange; failure/exit masks drop zero-weight
        # clients from the stash host-side
        self.variables, self.server_state, train_loss = self._lens_absorb(
            round_idx, out,
            np.arange(self.dataset.num_clients, dtype=np.int64),
            None if live is None else np.asarray(live) > 0)
        return train_loss if self.config.async_rounds else float(train_loss)

    def round_counts(self, round_idx: int) -> tuple:
        """Resident full-participation paths execute their own static
        schedule (no per-round bucketing), so report exactly that: every
        client's real records, and per-group size x scan_len (grouped) or
        cohort x n_pad (plain) executed slots."""
        if (self._packed_mesh is None and self._dev_groups is None
                and self._dev_sharded is None):
            return super().round_counts(round_idx)
        counts = np.asarray(self.dataset.train_counts, np.float64)
        live = self._sample_failures(round_idx, self.dataset.num_clients,
                                     record=False)
        if live is not None:
            counts = counts * live
        if self._client_active is not None:
            counts = counts * self._client_active
        if self._packed_mesh is not None:
            plan = self._packed_mesh["plan"]
            padded = (plan.executed_slots * self.config.batch_size
                      // max(self.config.epochs, 1))
        elif self._group_plan is not None:
            padded = sum(len(idx_g) * bucket for idx_g, bucket in self._group_plan)
        else:
            padded = int(self.dataset.train_x.shape[1]) * self.dataset.num_clients
        return int(counts.sum()), int(padded)

    def _crosssilo_hooks_checked(self) -> dict:
        hooks = self.crosssilo_hooks()
        if hooks is None:
            if type(self).aggregate is not FedAvgAPI.aggregate:
                raise NotImplementedError(
                    f"{type(self).__name__} overrides aggregate(), which the in-mesh "
                    "psum path cannot honor; implement crosssilo_hooks() (see "
                    "make_crosssilo_round), override build_round_step, or use the "
                    "simulation paradigm (FedAvgAPI)."
                )
            hooks = {}
        return hooks

    def build_round_step(self):
        from fedml_tpu.parallel.crosssilo import make_crosssilo_round, place_round_inputs
        from fedml_tpu.parallel.mesh import replicated

        round_fn = make_crosssilo_round(self._local_train, self.mesh,
                                        lens=self._lens_armed,
                                        **self._crosssilo_hooks_checked())

        def round_step(variables, server_state, cx, cy, cm, counts, rng):
            from fedml_tpu.parallel.mesh import global_put

            keys = jax.random.split(rng, cx.shape[0])
            variables, cx, cy, cm, counts, keys = place_round_inputs(
                self.mesh, variables, cx, cy, cm, counts, keys
            )
            server_state = global_put(server_state, replicated(self.mesh))
            return round_fn(variables, server_state, cx, cy, cm, counts, keys,
                            global_put(rng, replicated(self.mesh)))

        return round_step
