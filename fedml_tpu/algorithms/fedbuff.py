"""fedbuff: asynchronous buffered aggregation with staleness-weighted folds.

Every other paradigm in the tree is round-synchronous: a round broadcasts
one model, blocks on a barrier (or a straggler deadline that DROPS the
slow), aggregates, repeats. FedBuff (Nguyen et al., "Federated Learning
with Buffered Asynchronous Aggregation") removes the barrier: the server
keeps a model **version** counter, folds every client contribution into a
buffer the moment it is accepted, and emits a new version every ``K``
contributions. Clients train against whatever version they last pulled;
a contribution trained against version ``v`` folding while the server is
at version ``V`` has **staleness** ``V - v`` and folds with the decayed
weight

    ``weight = n * (1 + staleness) ** -alpha``            (``--buffer_k``,
                                                ``--buffer_staleness_alpha``)

so stragglers CONTRIBUTE (attenuated) instead of being discarded at a
deadline — robustness is the contract, not a feature.

Contributions are **update deltas** (client model minus the version it
trained from), not full weights: folding a half-stale full model would
drag the server back toward the old parameters, while a stale delta is
exactly the FedBuff update rule — and it keeps the server O(1): one
:class:`~fedml_tpu.core.streaming.StreamAccumulator` (PR 13) holds the
running weighted delta sum, one ``tree_add`` applies it at emission.
With ``buffer_k == cohort`` and zero staleness an emission is

    ``G + sum(n_i * (w_i - G)) / sum(n_i)  ==  sum(n_i * w_i) / sum(n_i)``

— the plain FedAvg weighted mean, which is the sync-equivalence pin
(tests/test_fedbuff.py).

Fold order (``--buffer_mode``, mirroring ``--stream_aggregate``):

- ``arrival``: fold the moment an upload lands — the production fast
  path. Results depend on arrival order (which folds share a version, and
  float summation order inside one).
- ``deterministic``: folds advance through the canonical ``(tag, worker)``
  frontier (:class:`DeterministicFrontier`): worker ``w``'s ``t``-th
  contribution folds only after every ``(t', w')`` with
  ``(t', w') < (t, w)`` that CAN still arrive has folded. Because a worker
  only trains its ``t``-th assignment after the server answered its
  ``(t-1)``-th fold, the frontier never deadlocks on a live worker; a
  crash-stopped worker's slots are skipped at ejection — and since an
  ejected worker contributes nothing past its crash point anyway, the
  fold SEQUENCE (and therefore every version's membership, every
  staleness value, every weight) is a pure function of
  ``(seed, chaos_seed)``: the whole async schedule replays bit-identically
  (the chaos crash fate counts protocol progress, comm/chaos.py). The one
  arrival-dependent event is crash_restart RE-ADMISSION — a revived
  worker re-enters at whatever frontier sweep its JOIN happens to land
  in, so replay pins cover drop/dup/delay/crash-stop, and the restart
  tests pin behavior (rejoins, correct staleness), not bits.

This module is the transport-free server-side logic; the async edge
protocol lives in distributed/fedbuff_edge.py. DESIGN.md §18 has the
weighting math, the determinism argument, and the degradation table.

fedlens note: because every upload here already IS a raw update delta,
the edge manager's lens feed (``--lens on``) gets per-client update norms
for free at fold time, and scores alignment against the LAST emitted
server update (an async fold has no same-version cohort mean to compare
against) — see ``FedBuffEdgeServerManager._fold`` and DESIGN.md §22.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Optional

import numpy as np

from fedml_tpu.core.streaming import StreamAccumulator

__all__ = ["DeterministicFrontier", "FedBuffBuffer", "staleness_weight"]

Pytree = Any


def staleness_weight(n: float, staleness: int, alpha: float) -> float:
    """The FedBuff fold weight: sample count decayed polynomially in the
    version lag — ``n * (1 + staleness)^-alpha``. ``alpha == 0`` disables
    the decay (pure sample weighting); staleness 0 is always undecayed."""
    s = max(int(staleness), 0)
    return float(n) * float(1 + s) ** -float(alpha)


class FedBuffBuffer:
    """Versioned staleness-weighted delta buffer (module docstring).

    Thread-safe (the edge server's handler thread and the reliable layer's
    control injections serialize upstream, but the probe/keepalive timers
    do not). The accumulator always folds in the order :meth:`fold` is
    called — the CALLER owns the order contract: the deterministic
    frontier feeds canonical order, the arrival path feeds arrival order.
    """

    def __init__(self, k: int, alpha: float = 0.5, fold_log_cap: int = 4096):
        if k < 1:
            raise ValueError(f"buffer_k must be >= 1, got {k}")
        self.k = int(k)
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        self._acc = StreamAccumulator("arrival")
        #: the server's model version: bumped at every emission
        self.version = 0
        #: folds since the last emission (resets at emission)
        self.pending = 0
        #: lifetime fold count — the exact-once accounting surface
        self.folds = 0
        self.zero_weight_folds = 0
        self.versions_emitted = 0
        #: bounded per-fold record trail for tests/diagnostics:
        #: (version-at-fold, staleness, weight, n)
        self.fold_log: deque = deque(maxlen=int(fold_log_cap))
        #: staleness values folded into the CURRENT pending version
        self._pending_staleness: list[int] = []

    def fold(self, delta: Pytree, n: float, trained_version: int) -> dict:
        """Fold one contribution's update delta; returns the fold record
        (``staleness``, ``weight``). Staleness is measured against the
        CURRENT version at fold time — in deterministic mode that makes it
        a pure function of the canonical fold sequence."""
        with self._lock:
            staleness = max(self.version - int(trained_version), 0)
            weight = staleness_weight(n, staleness, self.alpha)
            self._acc.add(self.folds, delta, weight)
            self.folds += 1
            self.pending += 1
            if weight <= 0.0:
                self.zero_weight_folds += 1
            self._pending_staleness.append(staleness)
            rec = {"version": self.version, "staleness": staleness,
                   "weight": weight, "n": float(n)}
            self.fold_log.append(rec)
            return rec

    @property
    def ready(self) -> bool:
        # locked: fold() bumps pending on whichever thread delivers the
        # contribution; a torn check here could miss the K-th fold
        with self._lock:
            return self.pending >= self.k

    def emit(self, params: Pytree) -> tuple[Pytree, dict]:
        """Close the pending buffer into a new model version:
        ``params + weighted_mean(deltas)`` (an all-zero-weight buffer is
        the elastic no-op — params unchanged, version still bumps so lag
        accounting stays monotone). Returns ``(new_params, emission
        record)``."""
        from fedml_tpu.core.pytree import tree_add

        with self._lock:
            mean_delta = self._acc.finalize(params)
            stal = self._pending_staleness
            rec = {
                "version": self.version + 1,
                "folds": self.pending,
                "staleness_max": max(stal, default=0),
                "staleness_mean": (round(float(np.mean(stal)), 4)
                                   if stal else 0.0),
            }
            self._acc = StreamAccumulator("arrival")
            self.pending = 0
            self._pending_staleness = []
            self.version += 1
            self.versions_emitted += 1
        if mean_delta is not None:
            params = tree_add(params, mean_delta)
        return params, rec

    @property
    def nbytes(self) -> int:
        """Measured buffer footprint: ONE model-shaped running sum,
        independent of K and of how many contributions folded."""
        return self._acc.nbytes


class DeterministicFrontier:
    """Canonical ``(tag, worker)`` fold-order frontier for deterministic
    mode.

    Each admitted worker has a next expected train tag; the frontier's
    head is the minimum ``(tag, worker)`` over admitted workers. Offered
    contributions are held until they reach the head; :meth:`drain` yields
    them in canonical order. Ejecting a worker removes its slots — the
    relative order of everyone else's folds is unchanged, which is why a
    late ejection (the gave-up detection latency is wall-clock) cannot
    change the fold sequence: the ejected worker's missing slots were
    never going to arrive. NOT thread-safe; the owning server serializes
    access on its receive loop.
    """

    def __init__(self, workers):
        #: worker -> next expected tag (admitted workers only)
        self._next: dict[int, int] = {int(w): 0 for w in workers}
        self._held: dict[tuple[int, int], Any] = {}
        self.peak_held = 0

    @property
    def admitted(self) -> set:
        return set(self._next)

    def head(self) -> Optional[tuple[int, int]]:
        """The canonical slot the frontier is waiting on, or None when no
        worker is admitted."""
        if not self._next:
            return None
        return min((t, w) for w, t in self._next.items())

    def offer(self, worker: int, tag: int, item) -> bool:
        """Hold one contribution at its canonical slot. Returns False (a
        duplicate / already-folded slot / unadmitted worker) when the
        contribution must not fold."""
        w, t = int(worker), int(tag)
        nxt = self._next.get(w)
        if nxt is None or t < nxt or (t, w) in self._held:
            return False
        self._held[(t, w)] = item
        self.peak_held = max(self.peak_held, len(self._held))
        return True

    def drain(self):
        """Yield held contributions in canonical order while the head slot
        is available."""
        while True:
            head = self.head()
            if head is None or head not in self._held:
                return
            item = self._held.pop(head)
            t, w = head
            self._next[w] = t + 1
            yield w, t, item

    def eject(self, worker: int) -> None:
        """Remove a (dead) worker: its future slots stop gating the
        frontier; anything it had held is discarded."""
        w = int(worker)
        self._next.pop(w, None)
        for slot in [s for s in self._held if s[1] == w]:
            self._held.pop(slot)

    def admit(self, worker: int, from_tag: int) -> None:
        """(Re-)admit a worker starting at ``from_tag`` — the rejoin path.
        In deterministic mode the re-admission sweep is the one
        arrival-dependent event (class docstring)."""
        self._next[int(worker)] = int(from_tag)

    def next_tag(self, worker: int) -> Optional[int]:
        return self._next.get(int(worker))
