"""Silo training harness — the fork's cross-silo workflow.

Counterpart of the fork's silo variants (fedml_api/standalone/fedavg/
silo_fedavg.py:11-162, silo_fedopt.py:13, silo_fednova.py:12,
silo_fedagc.py:31) and fedml_core/instances/ (Client with trn/val/tst splits
and history, client.py:6-83): all clients participate every round, validation
drives early stopping, the best model is saved, and per-client + GLOBAL
histories are recorded with a pluggable ``history_save_fn``.

Implemented as a harness over ANY algorithm API (FedAvg/FedOpt/FedNova/
FedAGC/...), since the fork's four silo classes differ only in aggregation.
"""

from __future__ import annotations

import logging
import os
from collections import defaultdict
from typing import Callable, Optional, Type

import jax
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.data import FedDataset
from fedml_tpu.parallel.local import finalize_metrics
from fedml_tpu.utils.checkpoint import save_checkpoint

log = logging.getLogger(__name__)


class SiloRunner:
    """Early-stopping round loop around an algorithm API.

    Two stopping scopes:

    - GLOBAL (``patience``): the fork's validation-driven stop — training
      ends when the global metric stalls (silo_fedavg.py:87-95).
    - PER-CLIENT (``client_patience``, off by default): a client whose own
      metric stalls EXITS the federation — its aggregation weight zeroes
      on every schedule, and under the packed schedule its lane span
      becomes a structural no-op in the SAME compiled program
      (FedAvgAPI.set_client_active -> parallel/packed.mask_plan_arrays):
      masked lane freeze/exit, never a vmap fallback or a recompile.
      Exits take effect from the next round (next superstep block on the
      packed-mesh superstep path).
    """

    def __init__(
        self,
        dataset: FedDataset,
        config: FedConfig,
        api_cls: Type[FedAvgAPI] = FedAvgAPI,
        bundle=None,
        patience: int = 10,
        min_delta: float = 0.0,
        model_dir: Optional[str] = None,
        history_save_fn: Optional[Callable[[dict], None]] = None,
        client_patience: Optional[int] = None,
        client_min_delta: float = 0.0,
    ):
        # silo mode: every client participates every round (silo_fedavg.py:55)
        config = config.replace(
            client_num_per_round=min(config.client_num_in_total, dataset.num_clients),
            client_num_in_total=min(config.client_num_in_total, dataset.num_clients),
        )
        self.api = api_cls(dataset, config, bundle)
        self.patience = patience
        self.min_delta = min_delta
        self.model_dir = model_dir
        self.history_save_fn = history_save_fn
        self.client_patience = client_patience
        self.client_min_delta = client_min_delta
        n = self.api.dataset.num_clients
        self._client_best = np.full(n, -np.inf)
        self._client_stall = np.zeros(n, np.int64)
        self._client_on = np.ones(n, bool)
        self.history: dict[str, list] = defaultdict(list)
        self.best_metric = -np.inf
        self.best_round = -1

    @staticmethod
    def _validation_metric(m: dict) -> float:
        """Early-stopping metric from an already-computed global eval (the
        fork early-stops on validation accuracy, silo_fedavg.py:87-95); falls
        back to -loss only when accuracy is absent (not when it is 0.0)."""
        acc = m.get("acc")
        if acc is not None:
            return float(acc)
        return -float(m.get("loss", np.inf))

    def _eval_client(self, idx: int) -> dict:
        ds = self.api.dataset
        x, y, mask = ds.train_x[idx], ds.train_y[idx], ds.train_mask[idx]
        sums = self.api._eval(self.api.variables, x, y, mask)
        return finalize_metrics(jax.tree.map(np.asarray, sums))

    def train(self) -> dict:
        cfg = self.api.config
        stall = 0
        for r in range(cfg.comm_round):
            # float() per run_round's contract: under async_rounds the
            # return is an un-synced device scalar, and this history is
            # host data (json-serialized by history_save_fn)
            train_loss = float(self.api.run_round(r))
            gm = self.api.evaluate_global()
            val = self._validation_metric(gm)
            self.history["round"].append(r)
            self.history["GLOBAL/Train/Loss"].append(train_loss)
            self.history["GLOBAL/Test/Acc"].append(gm.get("acc"))
            self.history["GLOBAL/Test/Loss"].append(gm.get("loss"))
            # per-client histories (fork logs Client.<id> metrics,
            # instances/client.py:59-60) + per-client early EXIT
            if r % cfg.frequency_of_the_test == 0:
                exited = False
                for c in range(self.api.dataset.num_clients):
                    if not self._client_on[c]:
                        # exited clients stop costing eval passes too —
                        # None keeps the per-round history lists aligned
                        self.history[f"Client.{c}/Train/Acc"].append(None)
                        continue
                    cm = self._eval_client(c)
                    self.history[f"Client.{c}/Train/Acc"].append(cm.get("acc"))
                    if self.client_patience:
                        cv = self._validation_metric(cm)
                        if cv > self._client_best[c] + self.client_min_delta:
                            self._client_best[c] = cv
                            self._client_stall[c] = 0
                        else:
                            self._client_stall[c] += 1
                            if self._client_stall[c] >= self.client_patience:
                                self._client_on[c] = False
                                exited = True
                                self.history[
                                    f"Client.{c}/stopped_round"].append(r)
                                log.info("client %d early-exits at round %d "
                                         "(best %g)", c, r,
                                         self._client_best[c])
                if exited:
                    if not self._client_on.any():
                        # everyone exited: stop instead of training no-op
                        # (all-zero-weight, elastic-rollback) rounds
                        log.info("all clients early-exited at round %d", r)
                        self.api.set_client_active(None)
                        break
                    self.api.set_client_active(
                        self._client_on.astype(np.float32))

            if val > self.best_metric + self.min_delta:
                self.best_metric, self.best_round, stall = val, r, 0
                if self.model_dir:
                    save_checkpoint(
                        os.path.join(self.model_dir, "model_best.ckpt"),
                        self.api.variables, self.api.server_state, r,
                        extra={"val": val},
                    )
            else:
                stall += 1
                if stall >= self.patience:
                    log.info("early stop at round %d (best %g @ %d)", r, self.best_metric, self.best_round)
                    break
        if self.model_dir:
            save_checkpoint(
                os.path.join(self.model_dir, "model_last.ckpt"),
                self.api.variables, self.api.server_state, r,
            )
        if self.history_save_fn:
            self.history_save_fn(dict(self.history))
        self.history["best_round"] = self.best_round
        self.history["best_metric"] = self.best_metric
        return dict(self.history)


def SiloFedAvg(dataset, config, **kw) -> SiloRunner:
    return SiloRunner(dataset, config, FedAvgAPI, **kw)


def SiloFedOpt(dataset, config, **kw) -> SiloRunner:
    from fedml_tpu.algorithms.fedopt import FedOptAPI

    return SiloRunner(dataset, config, FedOptAPI, **kw)


def SiloFedProx(dataset, config, **kw) -> SiloRunner:
    from fedml_tpu.algorithms.fedprox import FedProxAPI

    return SiloRunner(dataset, config, FedProxAPI, **kw)


def SiloFedNova(dataset, config, **kw) -> SiloRunner:
    from fedml_tpu.algorithms.fednova import FedNovaAPI

    return SiloRunner(dataset, config, FedNovaAPI, **kw)


def SiloFedAGC(dataset, config, **kw) -> SiloRunner:
    from fedml_tpu.algorithms.fedagc import FedAGCAPI

    return SiloRunner(dataset, config, FedAGCAPI, **kw)
