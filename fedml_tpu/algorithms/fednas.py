"""FedNAS — federated DARTS architecture search.

Counterpart of reference fedml_api/distributed/fednas/: every client runs
local differentiable architecture search — alternating architecture (alpha)
steps and weight steps (FedNASTrainer.local_search:82+, single-level mode =
architect.step_single_level:107-125) — and the server aggregates BOTH weight
and alpha pytrees by sample-weighted averaging
(FedNASAggregator.aggregate/__aggregate_alpha:70-107), recording the derived
genotype each round (record_model_global_architecture:173).

TPU re-design: because alphas are plain inputs of the pure search network
(models/darts.py), the client's search step is one jitted scan — alpha-grad
and weight-grad are two ``jax.grad`` argnums of the same function — and the
whole cohort searches under one ``vmap``. Aggregating alphas is the same
``tree_weighted_mean`` used for weights; no separate message type needed
(reference message_define.py MSG_ARG_KEY_ARCHS).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.pytree import tree_weighted_mean
from fedml_tpu.core.rng import round_key, sample_clients, seed_everything
from fedml_tpu.core.tasks import int_cross_entropy
from fedml_tpu.data import FedDataset
from fedml_tpu.models.darts import (
    DartsNetwork,
    DartsSearchNetwork,
    derive_genotype,
    init_alphas,
)

log = logging.getLogger(__name__)

# weight-optimizer hyperparameters (reference main_fednas defaults) — shared
# by self._wtx AND the unrolled architect's inner SGD step, which must stay
# in lockstep with the real optimizer
W_MOMENTUM = 0.9
W_WEIGHT_DECAY = 3e-4


def _masked_ce(logits, labels, mask):
    per = int_cross_entropy(logits, labels)
    m = mask.astype(jnp.float32)
    return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)


class FedNASAPI:
    """Standalone-simulation FedNAS search phase."""

    def __init__(
        self,
        dataset: FedDataset,
        config: FedConfig,
        channels: int = 8,
        layers: int = 4,
        steps: int = 2,
        multiplier: int = 2,
        arch_lr: float = 3e-4,
        arch_wd: float = 1e-3,
        unrolled: Optional[bool] = None,
    ):
        self.dataset = dataset
        self.config = config
        self.steps_cfg = steps
        self.multiplier = multiplier
        #: second-order (unrolled) architect; config --unrolled unless overridden
        self.unrolled = bool(getattr(config, "unrolled", 0)) if unrolled is None \
            else bool(unrolled)
        self.module = DartsSearchNetwork(
            channels=channels, layers=layers, steps=steps,
            multiplier=multiplier, output_dim=dataset.class_num,
        )
        self.root_key = seed_everything(config.seed)
        ex = jnp.zeros((2,) + tuple(dataset.train_x.shape[2:]), jnp.float32)
        self.alphas = init_alphas(jax.random.fold_in(self.root_key, 7), steps)
        self.variables = self.module.init(
            {"params": jax.random.fold_in(self.root_key, 8)}, ex, self.alphas,
            train=False,
        )
        # weight optimizer: SGD momentum 0.9 wd 3e-4 (reference main_fednas
        # defaults); arch optimizer: Adam lr 3e-4 wd 1e-3 (architect.py:23-27)
        self._wtx = optax.chain(
            optax.add_decayed_weights(W_WEIGHT_DECAY),
            optax.sgd(config.lr, momentum=W_MOMENTUM),
        )
        self._atx = optax.chain(
            optax.add_decayed_weights(arch_wd), optax.adam(arch_lr)
        )
        self._search_round = self._build_search_round()
        self._eval_fn = self._build_eval()
        self.genotypes: list = []
        self.history: list[dict] = []

    def _build_local_search(self):
        """One client's full local search (alternating alpha/weight steps
        over epochs of minibatches) as a pure function — vmapped by the
        simulator's round, shard_mapped by the cross-silo round."""
        module, cfg = self.module, self.config
        wtx, atx = self._wtx, self._atx
        bs = cfg.batch_size
        n_pad = int(self.dataset.train_x.shape[1])
        steps = n_pad // bs
        epochs = cfg.epochs
        unrolled = self.unrolled
        if unrolled and bs < 2:
            raise ValueError("unrolled architect splits each batch into "
                             "train/val halves; batch_size must be >= 2")

        def local_search(variables, alphas, x, y, mask, count, rng):
            wopt = wtx.init(variables["params"])
            aopt = atx.init(alphas)
            steps_real = jnp.ceil(count.astype(jnp.float32) / bs).astype(jnp.int32)

            def epoch_fn(carry, ekey):
                variables, alphas, wopt, aopt = carry
                perm = jax.random.permutation(ekey, n_pad)
                order = perm[jnp.argsort(-mask[perm], stable=True)]
                xs = x[order].reshape((steps, bs) + x.shape[1:])
                ys = y[order].reshape((steps, bs))
                ms = mask[order].reshape((steps, bs))

                def step_fn(carry, batch):
                    variables, alphas, wopt, aopt = carry
                    bx, by, bm, step_idx = batch
                    live = (step_idx < steps_real).astype(jnp.float32)

                    def loss_on(p, a, x_, y_, m_):
                        vin = dict(variables)
                        vin["params"] = p
                        logits, new_vars = module.apply(
                            vin, x_, a, train=True, mutable=["batch_stats"]
                        )
                        return _masked_ce(logits, y_, m_), new_vars

                    def loss_of(p, a):
                        return loss_on(p, a, bx, by, bm)

                    # 1) architecture step
                    if unrolled:
                        # second-order architect (architect.py:32-45 +
                        # _backward_step_unrolled): grad of the VALIDATION
                        # loss at the weights after one unrolled SGD step on
                        # the TRAIN loss. The reference approximates the
                        # second-order term with a finite-difference
                        # Hessian-vector product (architect.py:85-103); JAX
                        # differentiates through the inner update EXACTLY.
                        # Each batch is split 50/50 into train/val halves —
                        # the static-shape form of the reference's separate
                        # train/valid queues. INTERLEAVED (even/odd slots),
                        # not contiguous: the epoch order sorts real samples
                        # to the front, so a contiguous split would leave the
                        # tail partial batch's val half all-padding and those
                        # architect steps with zero validation signal.
                        bxt, byt, bmt = bx[0::2], by[0::2], bm[0::2]
                        bxv, byv, bmv = bx[1::2], by[1::2], bm[1::2]
                        rho, wd_w = W_MOMENTUM, W_WEIGHT_DECAY
                        trace = optax.tree_utils.tree_get(wopt, "trace")

                        def val_after_unroll(a):
                            g = jax.grad(
                                lambda p: loss_on(p, a, bxt, byt, bmt)[0]
                            )(variables["params"])
                            # torch-SGD unrolled step: w - eta*(rho*buf + g + wd*w)
                            # (reference _compute_unrolled_model:36-44)
                            p_un = jax.tree.map(
                                lambda p, gg, t: p - cfg.lr * (rho * t + gg + wd_w * p),
                                variables["params"], g, trace,
                            )
                            return loss_on(p_un, a, bxv, byv, bmv)[0]

                        a_grads = jax.grad(val_after_unroll)(alphas)
                    else:
                        # single-level: same batch (architect
                        # step_single_level:107-125)
                        a_grads = jax.grad(
                            lambda a: loss_of(variables["params"], a)[0]
                        )(alphas)
                    a_upd, new_aopt = atx.update(a_grads, aopt, alphas)
                    new_alphas = optax.apply_updates(alphas, a_upd)

                    # 2) weight step with the updated alphas (on the train
                    #    half when unrolled — the val half is held out)
                    if unrolled:
                        (l, new_vars), w_grads = jax.value_and_grad(
                            lambda p: loss_on(p, new_alphas, bxt, byt, bmt),
                            has_aux=True,
                        )(variables["params"])
                    else:
                        (l, new_vars), w_grads = jax.value_and_grad(
                            lambda p: loss_of(p, new_alphas), has_aux=True
                        )(variables["params"])
                    # reference main_fednas default --grad_clip is 5; a
                    # configured FedConfig.grad_clip overrides it
                    clip = cfg.grad_clip if cfg.grad_clip else 5.0
                    gn = optax.global_norm(w_grads)
                    scale = jnp.minimum(1.0, clip / jnp.maximum(gn, 1e-12))
                    w_grads = jax.tree.map(lambda g: g * scale, w_grads)
                    w_upd, new_wopt = wtx.update(w_grads, wopt, variables["params"])
                    new_params = optax.apply_updates(variables["params"], w_upd)

                    def freeze(new, old):
                        return jax.tree.map(
                            lambda n, o: live * n + (1.0 - live) * o
                            if jnp.issubdtype(n.dtype, jnp.floating)
                            else jnp.where(live > 0, n, o),
                            new, old,
                        )

                    out_vars = dict(freeze(
                        {k: v for k, v in new_vars.items() if k != "params"},
                        {k: v for k, v in variables.items() if k != "params"},
                    ))
                    out_vars["params"] = freeze(new_params, variables["params"])
                    return (
                        out_vars,
                        freeze(new_alphas, alphas),
                        freeze(new_wopt, wopt),
                        freeze(new_aopt, aopt),
                    ), l * live

                carry, losses = jax.lax.scan(
                    step_fn, (variables, alphas, wopt, aopt),
                    (xs, ys, ms, jnp.arange(steps)),
                )
                loss = jnp.sum(losses) / jnp.maximum(steps_real.astype(jnp.float32), 1.0)
                return carry, loss

            (variables, alphas, _, _), ep_losses = jax.lax.scan(
                epoch_fn, (variables, alphas, wopt, aopt),
                jax.random.split(rng, epochs),
            )
            return variables, alphas, ep_losses[-1]

        return local_search

    def _build_search_round(self):
        local_search = self._build_local_search()

        @jax.jit
        def search_round(variables, alphas, cx, cy, cm, counts, rng):
            keys = jax.random.split(rng, cx.shape[0])
            new_vars, new_alphas, losses = jax.vmap(
                local_search, in_axes=(None, None, 0, 0, 0, 0, 0)
            )(variables, alphas, cx, cy, cm, counts, keys)
            agg_vars = tree_weighted_mean(new_vars, counts)
            agg_alphas = tree_weighted_mean(new_alphas, counts)
            train_loss = jnp.sum(losses * counts) / jnp.sum(counts)
            return agg_vars, agg_alphas, train_loss

        return search_round

    def _build_eval(self):
        module = self.module

        @jax.jit
        def evaluate(variables, alphas, x, y, mask):
            logits = module.apply(variables, x, alphas, train=False)
            pred = jnp.argmax(logits, axis=-1)
            m = mask.astype(jnp.float32)
            per = int_cross_entropy(logits, y)
            return {
                "correct": jnp.sum((pred == y).astype(jnp.float32) * m),
                "loss_sum": jnp.sum(per * m),
                "count": jnp.sum(m),
            }

        return evaluate

    def train(self) -> dict:
        d, cfg = self.dataset, self.config
        last = {}
        t0 = time.time()
        for rnd in range(cfg.comm_round):
            population = min(cfg.client_num_in_total, d.num_clients)
            sampled = sample_clients(
                rnd, population, min(cfg.client_num_per_round, population),
                seed=cfg.seed,
            )
            cx, cy, cm, counts = d.client_slice(sampled)
            rk = round_key(self.root_key, rnd)
            self.variables, self.alphas, loss = self._search_round(
                self.variables, self.alphas, cx, cy, cm,
                jnp.asarray(counts, jnp.float32), rk,
            )
            g = derive_genotype(self.alphas, self.steps_cfg, self.multiplier)
            self.genotypes.append(g)
            if rnd % cfg.frequency_of_the_test == 0 or rnd == cfg.comm_round - 1:
                sums = jax.device_get(self._eval_fn(
                    self.variables, self.alphas,
                    jnp.asarray(d.test_x), jnp.asarray(d.test_y),
                    jnp.asarray(d.test_mask),
                ))
                acc = float(sums["correct"]) / max(float(sums["count"]), 1.0)
                last = {
                    "round": rnd, "Test/Acc": acc,
                    "Test/Loss": float(sums["loss_sum"]) / max(float(sums["count"]), 1.0),
                    "Train/Loss": float(loss),
                    "genotype": g,
                }
                self.history.append(last)
                log.info("FedNAS round %d acc %.4f genotype %s", rnd, acc, g)
        if self.history:
            self.history[-1]["rounds_per_sec"] = cfg.comm_round / (time.time() - t0)
        return last

    def build_discrete_network(self, channels: int = 16, layers: int = 8) -> DartsNetwork:
        """FedNAS phase 2: the searched genotype becomes a fixed network for
        federated training (reference search -> train pipeline)."""
        g = self.genotypes[-1] if self.genotypes else derive_genotype(
            self.alphas, self.steps_cfg, self.multiplier
        )
        return DartsNetwork(
            genotype=g, channels=channels, layers=layers,
            output_dim=self.dataset.class_num,
        )


class CrossSiloFedNASAPI(FedNASAPI):
    """FedNAS on the cross-silo mesh path: silos sharded over a 'clients'
    Mesh, each device searches its clients under vmap, and BOTH the weight
    and alpha pytrees aggregate by weighted psum on ICI — the in-mesh
    counterpart of the reference's rank-0 FedNASAggregator, which weighted-
    averages weights AND alphas across MPI ranks
    (distributed/fednas/FedNASAggregator.py:70-107 __aggregate +
    __aggregate_alpha). Both reductions are plain weighted means, so they
    ride one fused all-reduce; genotype derivation stays host-side on the
    replicated result, identical to the simulator."""

    def __init__(self, dataset, config, mesh=None, **kw):
        from fedml_tpu.parallel.mesh import client_mesh

        self.mesh = mesh or client_mesh()
        axis_sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        n_axis = axis_sizes.get("clients")
        if n_axis is None:
            raise ValueError(
                f"mesh must have a 'clients' axis, got {self.mesh.axis_names}")
        # validate the cohort train() actually samples: population is capped
        # by BOTH client_num_in_total and the dataset (see FedNASAPI.train)
        population = min(config.client_num_in_total, dataset.num_clients)
        cohort = min(config.client_num_per_round, population)
        if cohort % n_axis:
            raise ValueError(
                f"effective cohort size ({cohort}) must be a multiple of the "
                f"mesh 'clients' axis ({n_axis})")
        super().__init__(dataset, config, **kw)

    def _build_search_round(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from jax import shard_map

        local_search = self._build_local_search()
        mesh, axis = self.mesh, "clients"

        def shard_fn(variables, alphas, cx, cy, cm, counts, keys):
            from fedml_tpu.parallel.crosssilo import weighted_psum_tree_mean

            new_vars, new_alphas, losses = jax.vmap(
                local_search, in_axes=(None, None, 0, 0, 0, 0, 0)
            )(variables, alphas, cx, cy, cm, counts, keys)
            w = counts.astype(jnp.float32)
            denom = jnp.maximum(jax.lax.psum(jnp.sum(w), axis), 1e-12)
            agg_vars = weighted_psum_tree_mean(new_vars, w, axis, denom)
            agg_alphas = weighted_psum_tree_mean(new_alphas, w, axis, denom)
            loss = jax.lax.psum(jnp.sum(losses * w), axis) / denom
            return agg_vars, agg_alphas, loss

        # check_vma=False (like make_hierarchical_round): the architect's
        # adam state carries replicated-initialized scalars (step count)
        # through a scan over device-varying data, which the varying-axes
        # checker rejects. Safe here because every psum runs AFTER local
        # autodiff — no collective sits inside a differentiated region, so
        # the psum-transpose hazard (see tests pinning SP/PP exactness)
        # cannot arise.
        mapped = jax.jit(shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(), P(), P(axis), P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ))
        rep = NamedSharding(mesh, P())
        sharded = NamedSharding(mesh, P(axis))

        def search_round(variables, alphas, cx, cy, cm, counts, rng):
            # same key values as the simulator's in-jit split(rng, C)
            keys = jax.random.split(rng, cx.shape[0])
            variables, alphas = (jax.device_put(variables, rep),
                                 jax.device_put(alphas, rep))
            cx, cy, cm, counts, keys = (
                jax.device_put(jnp.asarray(a), sharded)
                for a in (cx, cy, cm, counts, keys))
            return mapped(variables, alphas, cx, cy, cm, counts, keys)

        return search_round
