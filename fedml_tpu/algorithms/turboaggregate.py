"""TurboAggregate — secure aggregation via finite-field MPC primitives.

Counterpart of reference fedml_api/standalone/turboaggregate/: Lagrange-coded
computing (LCC) + BGW polynomial secret sharing + additive secret sharing
(mpc_function.py:62-260) around a FedAvg round loop (TA_trainer.py:39-72),
with clients organised into groups that relay masked partial aggregates.

Re-design notes (vs the reference's per-element Python loops):
- every field operation is VECTORIZED numpy int64 over a prime field
  (default p = 2^31 - 1, Mersenne); modular inverse is Fermat
  exponentiation instead of the reference's iterative extended-Euclid
  (mpc_function.py:4-18) so it maps over arrays,
- model pytrees enter the field through fixed-point quantization
  (the reference's TA path also quantizes implicitly by operating on
  weights scaled to ints in the full Turbo-Aggregate system),
- the protocol is simulated host-side (like the reference's standalone
  trainer); local training stays the jitted vmapped program from FedAvg.

Correctness property tested: the secure aggregate equals the plain weighted
average to quantization tolerance, and LCC/BGW decode(encode(x)) == x.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI

log = logging.getLogger(__name__)

P_DEFAULT = np.int64(2**31 - 1)


def _require_rng(rng) -> np.random.Generator:
    """Every masking/share draw must come from a caller-seeded generator.

    The OS-entropy fallback (``default_rng()`` with no seed) these helpers
    used to carry made the shares — and any bug involving them —
    irreproducible across runs (fedlint seeded-rng). Accepts a Generator,
    or a seed (int / sequence) to derive one from.
    """
    if rng is None:
        raise ValueError(
            "rng is required: pass a np.random.Generator derived from the "
            "run seed (or the seed itself) — OS-entropy shares break run "
            "determinism"
        )
    if isinstance(rng, np.random.Generator):
        return rng
    return np.random.default_rng(rng)


# ---------------------------------------------------------------- field ops

def modpow(base: np.ndarray, exp: int, p: np.int64) -> np.ndarray:
    """Vectorized modular exponentiation (square-and-multiply). Safe because
    p < 2^31 keeps every product below 2^62 < int64 max."""
    result = np.ones_like(np.asarray(base, dtype=np.int64))
    b = np.mod(np.asarray(base, dtype=np.int64), p)
    e = int(exp)
    while e > 0:
        if e & 1:
            result = np.mod(result * b, p)
        b = np.mod(b * b, p)
        e >>= 1
    return result


def modular_inv(a: np.ndarray, p: np.int64 = P_DEFAULT) -> np.ndarray:
    """Fermat: a^(p-2) mod p (p prime) — vectorized replacement for the
    reference's scalar extended-Euclid loop (mpc_function.py:4-18)."""
    return modpow(a, int(p) - 2, p)


def lagrange_coeffs(
    alphas: np.ndarray, betas: np.ndarray, p: np.int64 = P_DEFAULT
) -> np.ndarray:
    """U[i, j] = prod_{k!=j}(alpha_i - beta_k) / prod_{k!=j}(beta_j - beta_k)
    mod p (mpc_function.py:38-57), computed with outer products."""
    alphas = np.mod(np.asarray(alphas, np.int64), p)
    betas = np.mod(np.asarray(betas, np.int64), p)
    A, B = len(alphas), len(betas)
    # num[i, j] = prod over k != j of (alpha_i - beta_k)
    diff_ab = np.mod(alphas[:, None] - betas[None, :], p)        # [A, B]
    num = np.ones((A, B), np.int64)
    den = np.ones((B,), np.int64)
    diff_bb = np.mod(betas[:, None] - betas[None, :], p)         # [B, B]
    for k in range(B):
        mask = np.arange(B) != k
        num[:, mask] = np.mod(num[:, mask] * diff_ab[:, k][:, None], p)
        den[mask] = np.mod(den[mask] * diff_bb[mask, k], p)
    return np.mod(num * modular_inv(den, p)[None, :], p)


# ------------------------------------------------------- BGW secret sharing

def bgw_encode(
    X: np.ndarray, N: int, T: int, p: np.int64 = P_DEFAULT,
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """Shamir/BGW: degree-T polynomial with constant term X evaluated at
    alpha_1..alpha_N (mpc_function.py:62-76). X [m, d] -> shares [N, m, d]."""
    rng = _require_rng(rng)
    X = np.mod(np.asarray(X, np.int64), p)
    coeffs = rng.integers(0, int(p), size=(T + 1,) + X.shape, dtype=np.int64)
    coeffs[0] = X
    alphas = np.arange(1, N + 1, dtype=np.int64)
    shares = np.zeros((N,) + X.shape, np.int64)
    for i in range(N):
        a_pow = np.int64(1)
        for t in range(T + 1):
            shares[i] = np.mod(shares[i] + coeffs[t] * a_pow, p)
            a_pow = np.mod(a_pow * alphas[i], p)
    return shares


def bgw_decode(
    shares: np.ndarray, worker_idx: Sequence[int], p: np.int64 = P_DEFAULT
) -> np.ndarray:
    """Reconstruct the secret from >=T+1 shares by Lagrange interpolation at
    0 (mpc_function.py:79-108). The degree-T polynomial needs T+1 points;
    fewer would interpolate a lower-degree polynomial through the wrong
    value — callers must know reconstruction failed, not get garbage."""
    worker_idx = np.asarray(worker_idx)
    if shares.shape[0] != len(worker_idx):
        raise ValueError("one share per worker index required")
    alphas = np.mod(worker_idx + 1, p).astype(np.int64)   # alpha_i = i + 1
    lam = lagrange_coeffs(np.zeros(1, np.int64), alphas, p)[0]   # [R]
    flat = shares.reshape(len(worker_idx), -1)
    out = np.zeros(flat.shape[1], np.int64)
    for r in range(len(worker_idx)):
        out = np.mod(out + lam[r] * flat[r], p)
    return out.reshape(shares.shape[1:])


# ------------------------------------------------ Lagrange-coded computing

def _lcc_points(N: int, K: int, T: int, p: np.int64):
    """Interpolation points (betas) and evaluation points (alphas). The
    reference centers BOTH ranges near 0 (mpc_function.py:124-126), which
    makes some alphas coincide with data betas — those workers then receive
    raw secret chunks in the clear, voiding the T-colluder privacy. We keep
    the reference's betas but place alphas in a disjoint range (a reference
    defect fixed, not replicated)."""
    n_beta = K + T
    stt_b = -int(np.floor(n_beta / 2))
    betas = np.mod(np.arange(stt_b, stt_b + n_beta), p).astype(np.int64)
    stt_a = stt_b + n_beta  # first point past the beta range
    alphas = np.mod(np.arange(stt_a, stt_a + N), p).astype(np.int64)
    return alphas, betas


def lcc_encode(
    X: np.ndarray, N: int, K: int, T: int, p: np.int64 = P_DEFAULT,
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """Split X [m, d] into K chunks + T random chunks, interpolate through
    them, evaluate at N points (mpc_function.py:111-134). Returns
    [N, m//K, d]."""
    rng = _require_rng(rng)
    X = np.mod(np.asarray(X, np.int64), p)
    m = X.shape[0]
    assert m % K == 0, "rows must divide evenly into K chunks"
    chunks = X.reshape(K, m // K, *X.shape[1:])
    if T:
        noise = rng.integers(0, int(p), size=(T,) + chunks.shape[1:], dtype=np.int64)
        chunks = np.concatenate([chunks, noise], axis=0)
    alphas, betas = _lcc_points(N, K, T, p)
    U = lagrange_coeffs(alphas, betas, p)                 # [N, K+T]
    flat = chunks.reshape(K + T, -1)
    out = np.zeros((N, flat.shape[1]), np.int64)
    for j in range(K + T):
        out = np.mod(out + U[:, j][:, None] * flat[j][None, :], p)
    return out.reshape((N,) + chunks.shape[1:])


def lcc_decode(
    f_eval: np.ndarray, N: int, K: int, T: int, worker_idx: Sequence[int],
    p: np.int64 = P_DEFAULT,
) -> np.ndarray:
    """Interpolate the chunk values back from evaluations at the surviving
    workers' points (mpc_function.py:197-213). For degree-1 (identity)
    computations any K+T workers suffice — and no fewer: the encoding
    polynomial has degree K+T-1."""
    if len(worker_idx) < K + T:
        raise ValueError(
            f"LCC reconstruction needs >= K+T = {K + T} shares, got {len(worker_idx)}"
        )
    alphas, betas = _lcc_points(N, K, T, p)
    eval_pts = alphas[np.asarray(worker_idx)]
    U = lagrange_coeffs(betas[:K], eval_pts, p)           # [K, R]
    flat = f_eval.reshape(len(worker_idx), -1)
    out = np.zeros((K, flat.shape[1]), np.int64)
    for r in range(len(worker_idx)):
        out = np.mod(out + U[:, r][:, None] * flat[r][None, :], p)
    return out.reshape((K,) + f_eval.shape[1:])


def additive_shares(
    x: np.ndarray, n_out: int, p: np.int64 = P_DEFAULT,
    rng: Optional[np.random.Generator] = None,
) -> np.ndarray:
    """n_out shares summing to x mod p (mpc_function.py:216-226)."""
    rng = _require_rng(rng)
    x = np.mod(np.asarray(x, np.int64), p)
    shares = rng.integers(0, int(p), size=(n_out - 1,) + x.shape, dtype=np.int64)
    last = np.mod(x - np.sum(np.mod(shares, p), axis=0), p)
    return np.concatenate([shares, last[None]], axis=0)


# ------------------------------------------------- fixed-point quantization

def quantize(x: np.ndarray, frac_bits: int = 20, p: np.int64 = P_DEFAULT) -> np.ndarray:
    """float -> field: round(x * 2^frac_bits) with negatives wrapped mod p."""
    scaled = np.rint(np.asarray(x, np.float64) * (1 << frac_bits)).astype(np.int64)
    return np.mod(scaled, p)


def dequantize(
    f: np.ndarray, frac_bits: int = 20, p: np.int64 = P_DEFAULT
) -> np.ndarray:
    """field -> float, interpreting values above p/2 as negatives."""
    f = np.asarray(f, np.int64)
    signed = np.where(f > int(p) // 2, f - int(p), f)
    return signed.astype(np.float64) / (1 << frac_bits)


def secure_weighted_sum(
    vectors: np.ndarray, weights: np.ndarray, group_size: int = 2,
    frac_bits: int = 20, p: np.int64 = P_DEFAULT, seed: int = 0,
) -> np.ndarray:
    """Turbo-Aggregate round: clients pre-scale their update by its weight,
    quantize into the field, additively share WITHIN their group, groups
    relay masked partial sums along the group ring, and only the final total
    leaves the field. No individual update is ever visible in the clear —
    each hop sees field-uniform masked sums only.

    vectors [C, D] float, weights [C] (sum to 1 for a weighted mean).
    Returns the aggregate [D] float.
    """
    rng = np.random.default_rng(seed)
    C, D = vectors.shape
    n_groups = max(1, C // group_size)
    field_total = np.zeros(D, np.int64)
    for g in range(n_groups):
        members = range(g, C, n_groups)  # round-robin grouping
        group_sum = np.zeros(D, np.int64)
        for c in members:
            q = quantize(vectors[c] * weights[c], frac_bits, p)
            shares = additive_shares(q, group_size, p, rng)
            # every member accumulates its share; the in-field sum of the
            # group's shares equals the group's quantized contribution
            group_sum = np.mod(group_sum + np.sum(shares, axis=0) % p, p)
        # ring relay: the running total is itself masked (share sums are
        # uniform until the final unmasking)
        field_total = np.mod(field_total + group_sum, p)
    return dequantize(field_total, frac_bits, p)


class TurboAggregateAPI(FedAvgAPI):
    """FedAvg with the aggregation step replaced by the secure MPC path
    (TA_trainer.py:39-72). Local training stays the jitted vmapped program;
    the protocol runs host-side over quantized flat updates."""

    def __init__(self, dataset, config, bundle=None, group_size: int = 2,
                 frac_bits: int = 20):
        self.group_size = group_size
        self.frac_bits = frac_bits
        super().__init__(dataset, config, bundle)

    def build_round_step(self):
        local_train = self._local_train

        @jax.jit
        def train_only(variables, cx, cy, cm, counts, rng):
            keys = jax.random.split(rng, cx.shape[0])
            res = jax.vmap(local_train, in_axes=(None, 0, 0, 0, 0, 0))(
                variables, cx, cy, cm, counts, keys
            )
            train_loss = jnp.sum(res.train_loss * counts) / jnp.sum(counts)
            return res.variables, train_loss

        def round_step(variables, server_state, cx, cy, cm, counts, rng):
            stacked, train_loss = train_only(variables, cx, cy, cm, counts, rng)
            host = jax.tree.map(np.asarray, stacked)
            leaves, treedef = jax.tree.flatten(host)
            shapes = [l.shape[1:] for l in leaves]
            sizes = [int(np.prod(s)) for s in shapes]
            C = leaves[0].shape[0]
            flat = np.concatenate(
                [l.reshape(C, -1).astype(np.float64) for l in leaves], axis=1
            )
            w = np.asarray(counts, np.float64)
            w = w / w.sum()
            agg = secure_weighted_sum(
                flat, w, self.group_size, self.frac_bits, seed=int(np.sum(counts))
            )
            out_leaves, off = [], 0
            for s, sz, l in zip(shapes, sizes, leaves):
                out_leaves.append(agg[off : off + sz].reshape(s).astype(l.dtype))
                off += sz
            new_vars = jax.tree.unflatten(treedef, out_leaves)
            new_vars = jax.tree.map(jnp.asarray, new_vars)
            return new_vars, server_state, train_loss

        return round_step
