"""Centralized (non-FL) baseline trainer over the same federated dataset —
the sanity baseline and the other half of the federated==centralized
equivalence gate (reference fedml_api/centralized/centralized_trainer.py:9-104
and CI-script-fedavg.sh:43-47).

Implementation: the federation's records are merged into ONE logical client
and trained with the same jitted local-train program — so the equivalence
test compares two code paths that share only the math, not the loop.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.rng import round_key, seed_everything
from fedml_tpu.core.tasks import get_task
from fedml_tpu.data import FedDataset
from fedml_tpu.data.batching import pad_to_multiple
from fedml_tpu.models import ModelBundle, create_model
from fedml_tpu.parallel.local import finalize_metrics, make_eval_fn, make_local_train_fn


def merge_clients(dataset: FedDataset, batch_size: int):
    """Flatten the stacked per-client arrays back into one masked pool."""
    C, n_pad = dataset.train_mask.shape
    flat_x = dataset.train_x.reshape((C * n_pad,) + dataset.train_x.shape[2:])
    flat_y = dataset.train_y.reshape((C * n_pad,) + dataset.train_y.shape[2:])
    flat_m = dataset.train_mask.reshape(-1)
    keep = flat_m > 0
    x, y = flat_x[keep], flat_y[keep]
    n = pad_to_multiple(len(x), batch_size)
    pad = n - len(x)
    if pad:
        x = np.concatenate([x, x[:pad]])
        y = np.concatenate([y, y[:pad]])
    m = np.concatenate([np.ones(len(flat_m[keep]), np.float32), np.zeros(pad, np.float32)])
    return x, y, m


class CentralizedTrainer:
    def __init__(self, dataset: FedDataset, config: FedConfig, bundle: ModelBundle | None = None):
        self.dataset = dataset
        self.config = config
        self.bundle = bundle or create_model(
            config.model, dataset.class_num, input_shape=dataset.train_x.shape[2:] or None
        )
        self.task = get_task(dataset.task, dataset.class_num)
        self.root_key = seed_everything(config.seed)
        self.variables = self.bundle.init(self.root_key)
        self.x, self.y, self.mask = merge_clients(dataset, config.batch_size)
        from fedml_tpu.parallel.local import local_train_kwargs

        self._train = jax.jit(make_local_train_fn(
            self.bundle, self.task, **local_train_kwargs(config),
        ))
        self._eval = make_eval_fn(self.bundle, self.task)
        # ship the merged dataset ONCE: jnp.asarray inside the round loop
        # re-transferred the full array every round (600 MB/round at
        # flagship scale through the remote-device tunnel)
        from fedml_tpu.utils.dtypes import host_bf16_cast

        self._dev = (jax.device_put(host_bf16_cast(self.x, config.dtype)),
                     jax.device_put(self.y), jax.device_put(self.mask))
        self._count = float(self.mask.sum())
        # the device copies are the working set now; keep only them
        del self.x, self.y

    def train(self) -> dict:
        history = {"round": [], "Test/Acc": [], "Test/Loss": []}
        count = jnp.asarray(self._count)
        dx, dy, dm = self._dev
        for r in range(self.config.comm_round):
            res = self._train(
                self.variables, dx, dy, dm, count,
                round_key(self.root_key, r),
            )
            self.variables = res.variables
            if r % self.config.frequency_of_the_test == 0 or r == self.config.comm_round - 1:
                m = finalize_metrics(jax.tree.map(np.asarray, self._eval(
                    self.variables, self.dataset.test_x, self.dataset.test_y, self.dataset.test_mask
                )))
                history["round"].append(r)
                history["Test/Acc"].append(m.get("acc"))
                history["Test/Loss"].append(m.get("loss"))
        return history


class StreamingCentralizedTrainer:
    """Centralized training for datasets that do NOT fit on device: batches
    are assembled by the native threaded pipeline (fedml_tpu/native) and
    double-buffered onto the device while the previous step computes. One
    jitted per-batch SGD step with donated state; the device never waits on
    the Python interpreter for batch assembly."""

    def __init__(self, dataset: FedDataset, config: FedConfig, bundle: ModelBundle | None = None,
                 n_threads: int = 4, depth: int = 6, mesh=None):
        from fedml_tpu.parallel.local import make_optimizer

        self.dataset = dataset
        self.config = config
        self.mesh = mesh  # optional ('batch',) mesh: batch-sharded DP + sync-BN
        self.bundle = bundle or create_model(
            config.model, dataset.class_num, input_shape=dataset.train_x.shape[2:] or None
        )
        self.task = get_task(dataset.task, dataset.class_num)
        self.root_key = seed_everything(config.seed)
        self.variables = self.bundle.init(self.root_key)
        self.n_threads, self.depth = n_threads, depth
        x, y, mask = merge_clients(dataset, config.batch_size)
        keep = mask > 0
        self.x, self.y = x[keep], y[keep]
        self.tx = make_optimizer(config.client_optimizer, config.lr, config.momentum, config.wd)
        self.opt_state = self.tx.init(self.variables["params"])

        # One step builder for both paths: mesh=None compiles the plain
        # donated single-device step; a ('batch',) mesh adds GSPMD batch
        # sharding + sync-BN + grad all-reduce (nn.DataParallel counterpart,
        # GKTServerTrainer.py:28-29).
        from fedml_tpu.parallel.dataparallel import make_dp_train_step

        dp = make_dp_train_step(self.bundle, self.task, self.tx, self.mesh,
                                grad_clip=config.grad_clip)

        # drop_last=True fixes the batch size, so the all-ones mask is one
        # constant made (and, on a mesh, sharded) once — not per step
        ones_mask = jnp.ones(config.batch_size, jnp.float32)
        if self.mesh is not None:
            from fedml_tpu.parallel.dataparallel import place_batch

            ones_mask = place_batch(self.mesh, ones_mask)

            def step(variables, opt_state, bx, by, key):
                # pipeline batches arrive committed to one device; respread
                bx, by = place_batch(self.mesh, bx, by)
                return dp(variables, opt_state, bx, by, ones_mask, key)
        else:
            def step(variables, opt_state, bx, by, key):
                return dp(variables, opt_state, bx, by, ones_mask, key)

        self._step = step
        self._eval = make_eval_fn(self.bundle, self.task)

    def train(self) -> dict:
        from fedml_tpu.data.pipeline import HostPipeline, device_stream

        history = {"round": [], "Test/Acc": [], "Test/Loss": []}
        x, y = self.x, self.y
        if len(x) < self.config.batch_size:  # tiny sets: repeat to one batch
            reps = -(-self.config.batch_size // len(x))
            x = np.concatenate([x] * reps)[: self.config.batch_size]
            y = np.concatenate([y] * reps)[: self.config.batch_size]
        step_no = 0
        with HostPipeline(x, y, self.config.batch_size, seed=self.config.seed,
                          n_threads=self.n_threads, depth=self.depth,
                          drop_last=True) as pipe:
            for r in range(self.config.comm_round):
                for _ in range(self.config.epochs):
                    for bx, by in device_stream(pipe):
                        self.variables, self.opt_state, _ = self._step(
                            self.variables, self.opt_state, bx, by,
                            round_key(self.root_key, step_no))
                        step_no += 1
                if r % self.config.frequency_of_the_test == 0 or r == self.config.comm_round - 1:
                    m = finalize_metrics(jax.tree.map(np.asarray, self._eval(
                        self.variables, self.dataset.test_x, self.dataset.test_y,
                        self.dataset.test_mask)))
                    history["round"].append(r)
                    history["Test/Acc"].append(m.get("acc"))
                    history["Test/Loss"].append(m.get("loss"))
        return history
