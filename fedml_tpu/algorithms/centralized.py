"""Centralized (non-FL) baseline trainer over the same federated dataset —
the sanity baseline and the other half of the federated==centralized
equivalence gate (reference fedml_api/centralized/centralized_trainer.py:9-104
and CI-script-fedavg.sh:43-47).

Implementation: the federation's records are merged into ONE logical client
and trained with the same jitted local-train program — so the equivalence
test compares two code paths that share only the math, not the loop.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.rng import round_key, seed_everything
from fedml_tpu.core.tasks import get_task
from fedml_tpu.data import FedDataset
from fedml_tpu.data.batching import pad_to_multiple
from fedml_tpu.models import ModelBundle, create_model
from fedml_tpu.parallel.local import finalize_metrics, make_eval_fn, make_local_train_fn


def merge_clients(dataset: FedDataset, batch_size: int):
    """Flatten the stacked per-client arrays back into one masked pool."""
    C, n_pad = dataset.train_mask.shape
    flat_x = dataset.train_x.reshape((C * n_pad,) + dataset.train_x.shape[2:])
    flat_y = dataset.train_y.reshape((C * n_pad,) + dataset.train_y.shape[2:])
    flat_m = dataset.train_mask.reshape(-1)
    keep = flat_m > 0
    x, y = flat_x[keep], flat_y[keep]
    n = pad_to_multiple(len(x), batch_size)
    pad = n - len(x)
    if pad:
        x = np.concatenate([x, x[:pad]])
        y = np.concatenate([y, y[:pad]])
    m = np.concatenate([np.ones(len(flat_m[keep]), np.float32), np.zeros(pad, np.float32)])
    return x, y, m


class CentralizedTrainer:
    def __init__(self, dataset: FedDataset, config: FedConfig, bundle: ModelBundle | None = None):
        self.dataset = dataset
        self.config = config
        self.bundle = bundle or create_model(
            config.model, dataset.class_num, input_shape=dataset.train_x.shape[2:] or None
        )
        self.task = get_task(dataset.task, dataset.class_num)
        self.root_key = seed_everything(config.seed)
        self.variables = self.bundle.init(self.root_key)
        self.x, self.y, self.mask = merge_clients(dataset, config.batch_size)
        self._train = jax.jit(make_local_train_fn(
            self.bundle, self.task,
            optimizer=config.client_optimizer, lr=config.lr, momentum=config.momentum,
            wd=config.wd, epochs=config.epochs, batch_size=config.batch_size,
            grad_clip=config.grad_clip,
        ))
        self._eval = make_eval_fn(self.bundle, self.task)

    def train(self) -> dict:
        history = {"round": [], "Test/Acc": [], "Test/Loss": []}
        count = jnp.asarray(float(self.mask.sum()))
        for r in range(self.config.comm_round):
            res = self._train(
                self.variables, jnp.asarray(self.x), jnp.asarray(self.y),
                jnp.asarray(self.mask), count, round_key(self.root_key, r),
            )
            self.variables = res.variables
            if r % self.config.frequency_of_the_test == 0 or r == self.config.comm_round - 1:
                m = finalize_metrics(jax.tree.map(np.asarray, self._eval(
                    self.variables, self.dataset.test_x, self.dataset.test_y, self.dataset.test_mask
                )))
                history["round"].append(r)
                history["Test/Acc"].append(m.get("acc"))
                history["Test/Loss"].append(m.get("loss"))
        return history
