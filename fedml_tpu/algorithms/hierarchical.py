"""Hierarchical FL — two-tier client -> group -> global aggregation.

Counterpart of reference fedml_api/standalone/hierarchical_fl/ (Group.train
group.py:24-46, Trainer.train trainer.py:43-69; note the fork's import there
is broken — SURVEY.md §2.2). Semantics: each global round runs
``group_comm_round`` group rounds; within a group round every group trains
its clients from the group model and aggregates within the group; after the
group rounds, group models weighted-average into the global model.

The equivalence property (reference CI asserts it, CI-script-fedavg.sh:51-57):
with group_comm_round=1 the scheme equals flat FedAvg over all clients.

TPU mapping: groups are segments of the client axis (segment_sum aggregation,
fedml_tpu.core.aggregation.hierarchical_aggregate); on a 2-D
('group','clients') mesh the group psum rides ICI and the global reduce DCN
(SURVEY.md §2.6.5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.aggregation import hierarchical_aggregate
from fedml_tpu.core.pytree import tree_index, tree_weighted_mean
from fedml_tpu.core.rng import round_key


class HierarchicalFedAvgAPI(FedAvgAPI):
    """Standalone hierarchical simulator; clients assigned to groups
    round-robin (client i -> group i % group_num, like the reference's even
    split)."""

    def __init__(self, dataset, config, bundle=None):
        self.group_num = max(int(config.group_num), 1)
        self.group_comm_round = max(int(config.group_comm_round), 1)
        super().__init__(dataset, config, bundle)

    def build_round_step(self):
        local_train = self._local_train
        group_num = self.group_num
        group_rounds = self.group_comm_round

        @jax.jit
        def round_step(variables, server_state, cx, cy, cm, counts, rng):
            C = cx.shape[0]
            gids = jnp.arange(C) % group_num
            # group model state: [G, ...] starting from the global model
            group_vars = jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (group_num,) + x.shape), variables
            )

            def one_group_round(group_vars, gr_key):
                # every client trains from ITS group's current model
                client_vars = jax.tree.map(lambda g: g[gids], group_vars)
                keys = jax.random.split(gr_key, C)
                res = jax.vmap(local_train)(client_vars, cx, cy, cm, counts, keys)
                g_vars, _ = hierarchical_aggregate(res.variables, counts, gids, group_num)
                return g_vars, jnp.sum(res.train_loss * counts) / jnp.sum(counts)

            group_vars, losses = jax.lax.scan(
                one_group_round, group_vars, jax.random.split(rng, group_rounds)
            )
            # global: weighted average of group models by group sample mass
            gw = jax.ops.segment_sum(counts.astype(jnp.float32), gids, group_num)
            new_vars = tree_weighted_mean(group_vars, gw)
            return new_vars, server_state, losses[-1]

        return round_step


class CrossSiloHierarchicalFedAvgAPI(HierarchicalFedAvgAPI):
    """Hierarchical FL on a 2-D ('group', 'clients') device mesh — the
    deployable counterpart of the reference's process-tree hierarchical
    deployment (hierarchical_fl/trainer.py:43-69 nested loops over group
    processes). Group aggregation psums over the ICI-adjacent 'clients'
    axis every group round; the global group-model reduce crosses the
    'group' axis once per round (DCN on a real pod) — see
    parallel/crosssilo.make_hierarchical_round, which this wraps.

    Equivalence with the simulator is by construction: row g of the mesh
    holds clients {j*G+g} (the simulator's round-robin gid = i % G) and
    every client consumes the same per-round key the simulator's in-jit
    split produces (mesh-verified in tests/test_crosssilo.py and the
    dryrun portfolio).

    The effective cohort (full participation is the standard hierarchical
    deployment) must equal group_num x (a multiple of the mesh's clients
    axis).
    """

    def __init__(self, dataset, config, bundle=None, mesh=None):
        from fedml_tpu.parallel.mesh import hierarchical_mesh

        group_num = max(int(config.group_num), 1)
        if mesh is None:
            n_dev = len(jax.devices())
            if n_dev % group_num:
                raise ValueError(
                    f"group_num ({group_num}) must divide the device count "
                    f"({n_dev}) to build the ('group','clients') mesh")
            mesh = hierarchical_mesh(group_num, n_dev // group_num)
        self.mesh = mesh
        axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if set(mesh.axis_names) != {"group", "clients"}:
            raise ValueError(
                f"mesh must have ('group','clients') axes, got {mesh.axis_names}")
        cohort = min(config.client_num_per_round, dataset.num_clients)
        cpg_dev = axis_sizes["clients"]
        if group_num != axis_sizes["group"]:
            raise ValueError(
                f"config.group_num ({group_num}) != mesh 'group' axis "
                f"({axis_sizes['group']})")
        if cohort % group_num or (cohort // group_num) % cpg_dev:
            raise ValueError(
                f"effective cohort ({cohort}) must split into {group_num} "
                f"groups of a multiple of {cpg_dev} clients")
        super().__init__(dataset, config, bundle)

    def build_round_step(self):
        from fedml_tpu.parallel.crosssilo import make_hierarchical_round
        from fedml_tpu.parallel.mesh import replicated
        from jax.sharding import NamedSharding, PartitionSpec as P

        round_fn = make_hierarchical_round(
            self._local_train, self.mesh, group_rounds=self.group_comm_round)
        mesh, G, GR = self.mesh, self.group_num, self.group_comm_round
        data_sh = NamedSharding(mesh, P("group", "clients"))
        key_sh = NamedSharding(mesh, P(None, "group", "clients"))

        def round_step(variables, server_state, cx, cy, cm, counts, rng):
            C = cx.shape[0]
            cpg = C // G
            # row g holds clients {j*G+g} — the simulator's gid = i % G
            order = np.array([[j * G + g for j in range(cpg)] for g in range(G)])
            flat = order.ravel()

            def regroup(a):
                return jax.device_put(
                    jnp.asarray(a)[flat].reshape((G, cpg) + a.shape[1:]), data_sh)

            # per-client keys replicate the simulator's in-jit split exactly:
            # group-round r key for client i = split(split(rng, GR)[r], C)[i]
            gr_keys = jax.random.split(rng, GR)
            keys = jnp.stack([
                jax.random.split(k, C)[flat].reshape((G, cpg)) for k in gr_keys
            ])
            new_vars, loss = round_fn(
                jax.device_put(variables, replicated(mesh)),
                regroup(cx), regroup(cy), regroup(cm),
                regroup(jnp.asarray(counts, jnp.float32)),
                jax.device_put(keys, key_sh))
            return new_vars, server_state, loss

        return round_step
