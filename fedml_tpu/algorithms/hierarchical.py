"""Hierarchical FL — two-tier client -> group -> global aggregation.

Counterpart of reference fedml_api/standalone/hierarchical_fl/ (Group.train
group.py:24-46, Trainer.train trainer.py:43-69; note the fork's import there
is broken — SURVEY.md §2.2). Semantics: each global round runs
``group_comm_round`` group rounds; within a group round every group trains
its clients from the group model and aggregates within the group; after the
group rounds, group models weighted-average into the global model.

The equivalence property (reference CI asserts it, CI-script-fedavg.sh:51-57):
with group_comm_round=1 the scheme equals flat FedAvg over all clients.

TPU mapping: groups are segments of the client axis (segment_sum aggregation,
fedml_tpu.core.aggregation.hierarchical_aggregate); on a 2-D
('group','clients') mesh the group psum rides ICI and the global reduce DCN
(SURVEY.md §2.6.5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.aggregation import hierarchical_aggregate
from fedml_tpu.core.pytree import tree_index, tree_weighted_mean
from fedml_tpu.core.rng import round_key


class HierarchicalFedAvgAPI(FedAvgAPI):
    """Standalone hierarchical simulator; clients assigned to groups
    round-robin (client i -> group i % group_num, like the reference's even
    split)."""

    def __init__(self, dataset, config, bundle=None):
        self.group_num = max(int(config.group_num), 1)
        self.group_comm_round = max(int(config.group_comm_round), 1)
        super().__init__(dataset, config, bundle)

    def build_round_step(self):
        local_train = self._local_train
        group_num = self.group_num
        group_rounds = self.group_comm_round

        @jax.jit
        def round_step(variables, server_state, cx, cy, cm, counts, rng):
            C = cx.shape[0]
            gids = jnp.arange(C) % group_num
            # group model state: [G, ...] starting from the global model
            group_vars = jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (group_num,) + x.shape), variables
            )

            def one_group_round(group_vars, gr_key):
                # every client trains from ITS group's current model
                client_vars = jax.tree.map(lambda g: g[gids], group_vars)
                keys = jax.random.split(gr_key, C)
                res = jax.vmap(local_train)(client_vars, cx, cy, cm, counts, keys)
                g_vars, _ = hierarchical_aggregate(res.variables, counts, gids, group_num)
                return g_vars, jnp.sum(res.train_loss * counts) / jnp.sum(counts)

            group_vars, losses = jax.lax.scan(
                one_group_round, group_vars, jax.random.split(rng, group_rounds)
            )
            # global: weighted average of group models by group sample mass
            gw = jax.ops.segment_sum(counts.astype(jnp.float32), gids, group_num)
            new_vars = tree_weighted_mean(group_vars, gw)
            return new_vars, server_state, losses[-1]

        return round_step
