"""FL algorithm zoo (counterpart of fedml_api/{standalone,distributed,centralized}).

Every algorithm composes two primitives:
- a jitted local-train function (fedml_tpu.parallel.local), and
- an aggregation rule (fedml_tpu.core.aggregation),
run either as vmap-over-clients simulation (standalone paradigm) or
shard_map-over-mesh (cross-silo distributed paradigm).
"""
