"""FedOpt — server-side adaptive optimization (Reddi et al. 2020).

Counterpart of reference fedml_api/standalone/fedopt/fedopt_api.py:13-152 and
distributed/fedopt/FedOptAggregator.py:70-120: the server treats
(w_global - w_avg) as a pseudo-gradient and feeds it to a server optimizer.
The reference resolves torch optimizers by reflection (OptRepo,
optrepo.py:7-64) and re-instantiates them per round, carefully copying state
back (FedOptAggregator._instantiate_opt); here the server optimizer is an
optax transformation whose state is threaded through the jitted round step —
state is never rebuilt, matching torch semantics without the gymnastics.

Supported server optimizers (--server_optimizer): sgd (FedAvgM when
server_momentum>0), adam (FedAdam), adagrad (FedAdagrad), yogi (FedYogi).
"""

from __future__ import annotations

import jax
import optax

from fedml_tpu.algorithms.fedavg import CrossSiloFedAvgAPI, FedAvgAPI
from fedml_tpu.core.pytree import tree_sub, tree_weighted_mean
from fedml_tpu.parallel.local import LocalResult


def make_server_optimizer(name: str, lr: float, momentum: float = 0.0) -> optax.GradientTransformation:
    name = name.lower()
    if name == "sgd":
        return optax.sgd(lr, momentum=momentum if momentum else None)
    if name == "adam":
        # FedAdam uses a large eps (1e-3 in the paper); reference uses torch
        # defaults — keep optax defaults for parity with torch Adam.
        return optax.adam(lr)
    if name == "adagrad":
        return optax.adagrad(lr)
    if name == "yogi":
        return optax.yogi(lr)
    raise ValueError(f"unknown server optimizer {name!r}")


class FedOptAPI(FedAvgAPI):
    """FedAvg with a persistent server optimizer over the pseudo-gradient."""

    def __init__(self, dataset, config, bundle=None, **kw):
        self._server_tx = make_server_optimizer(
            config.server_optimizer, config.server_lr, config.server_momentum
        )
        super().__init__(dataset, config, bundle, **kw)

    def init_server_state(self):
        return {"opt": self._server_tx.init(self.variables["params"])}

    def aggregate(self, variables, stacked_vars, counts, infos: LocalResult, rng, server_state):
        avg = tree_weighted_mean(stacked_vars, counts)
        # pseudo-gradient = w_global - w_avg (reference fedopt_api.py:139-152);
        # optax MINIMIZES, i.e. applies -lr * grad, so stepping along
        # (w_global - w_avg) moves toward the client average.
        pseudo_grad = tree_sub(variables["params"], avg["params"])
        updates, opt_state = self._server_tx.update(
            pseudo_grad, server_state["opt"], variables["params"]
        )
        new_params = optax.apply_updates(variables["params"], updates)
        new_vars = dict(avg)  # non-param collections (batch_stats) take the average
        new_vars["params"] = new_params
        return new_vars, {"opt": opt_state}

    def crosssilo_hooks(self):
        """The hook translation of :meth:`aggregate` — defined on the BASE
        class (not the CrossSilo variant) because it is the shared
        aggregation contract of BOTH non-vmap execution forms: the mesh
        psum tail AND the packed lane schedule's simulation round
        (FedAvgAPI._packing_hooks), so FedOpt rides the packed MXU fast
        path in every paradigm."""
        tx = self._server_tx

        def server_update(vars0, agg, extras, total, server_state, rng):
            pseudo_grad = tree_sub(vars0["params"], agg["params"])
            updates, opt_state = tx.update(
                pseudo_grad, server_state["opt"], vars0["params"]
            )
            new_params = optax.apply_updates(vars0["params"], updates)
            new_vars = dict(agg)
            new_vars["params"] = new_params
            return new_vars, {"opt": opt_state}

        return dict(server_update=server_update)


class CrossSiloFedOptAPI(CrossSiloFedAvgAPI, FedOptAPI):
    """FedOpt on the cross-silo mesh path: the weighted psum produces the
    client average on every device, then the server optimizer step runs
    replicated post-collective — the in-mesh counterpart of the reference's
    rank-0 FedOptAggregator (distributed/fedopt/FedOptAggregator.py:70-120),
    with no server rank and the optimizer state threaded through the one
    jitted round program (hooks on FedOptAPI.crosssilo_hooks)."""
