"""SplitNN — split learning with a client/server layer cut.

Reference: fedml_api/distributed/split_nn/ — the model's lower layers live
on each client, the upper layers on the server; per minibatch the client
sends activations forward and receives activation-gradients back
(client.py:24-34, server.py:40-60); clients take turns in a ring via a
semaphore token (client_manager.py:29-52), the server rotates
``active_node`` per epoch (server.py:70).

TPU-native redesign (SURVEY.md §7 hard part (c)): in-datacenter the stage
boundary is NOT a wire — client forward, server forward/backward, and both
optimizer updates are ONE fused jitted program per minibatch batch-scan, so
the per-batch round trip that dominates the reference (SURVEY.md §3.3 "hot
loop = per-batch round trip!") costs nothing. The relay ring (client k
trains an epoch, token passes to k+1) is preserved as the ALGORITHM —
sequential by design, it's what makes SplitNN SplitNN. The message-driven
variant for genuinely remote clients lives in
fedml_tpu/distributed/split_nn_edge.py with the same per-batch protocol as
the reference.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.core.config import FedConfig
from fedml_tpu.core.rng import round_key, seed_everything
from fedml_tpu.core.tasks import get_task
from fedml_tpu.data import FedDataset
from fedml_tpu.models import ModelBundle
from fedml_tpu.parallel.local import make_optimizer

log = logging.getLogger(__name__)


def make_splitnn_epoch_fn(
    client_bundle: ModelBundle,
    server_bundle: ModelBundle,
    task,
    tx_client: optax.GradientTransformation,
    tx_server: optax.GradientTransformation,
    batch_size: int,
):
    """Build ``epoch(cvars, svars, c_opt, s_opt, x, y, mask, count, rng)`` —
    one client-epoch of fused two-stage SGD as a single jitted scan.

    The reference's per-batch exchange (acts fwd / grads bwd over MPI,
    SURVEY.md §3.3) becomes a single jax.grad through both stages: XLA sees
    client-fwd -> server-fwd -> loss -> server-bwd -> client-bwd as one
    graph and fuses the boundary away.
    """

    @jax.jit
    def epoch(cvars, svars, c_opt, s_opt, x, y, mask, count, rng):
        n_pad = x.shape[0]
        steps = n_pad // batch_size
        steps_real = jnp.ceil(count.astype(jnp.float32) / batch_size).astype(jnp.int32)
        perm = jax.random.permutation(rng, n_pad)
        order = perm[jnp.argsort(-mask[perm], stable=True)]
        xs = x[order].reshape((steps, batch_size) + x.shape[1:])
        ys = y[order].reshape((steps, batch_size) + y.shape[1:])
        ms = mask[order].reshape((steps, batch_size))

        def step(carry, batch):
            cvars, svars, c_opt, s_opt = carry
            bx, by, bm, idx = batch
            live = (idx < steps_real).astype(jnp.float32)

            def loss_fn(cparams, sparams):
                acts = client_bundle.module.apply({**cvars, "params": cparams}, bx, train=True)
                logits = server_bundle.module.apply({**svars, "params": sparams}, acts, train=True)
                return task.loss(logits, by, bm)

            loss, (gc, gs) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
                cvars["params"], svars["params"]
            )

            def apply(tx, grads, opt, params):
                updates, new_opt = tx.update(grads, opt, params)
                new_params = optax.apply_updates(params, updates)
                freeze = lambda n, o: live * n + (1.0 - live) * o
                return jax.tree.map(freeze, new_params, params), jax.tree.map(freeze, new_opt, opt)

            cparams, c_opt = apply(tx_client, gc, c_opt, cvars["params"])
            sparams, s_opt = apply(tx_server, gs, s_opt, svars["params"])
            return ({**cvars, "params": cparams}, {**svars, "params": sparams}, c_opt, s_opt), loss * live

        (cvars, svars, c_opt, s_opt), losses = jax.lax.scan(
            step, (cvars, svars, c_opt, s_opt), (xs, ys, ms, jnp.arange(steps))
        )
        mean_loss = jnp.sum(losses) / jnp.maximum(steps_real.astype(jnp.float32), 1.0)
        return cvars, svars, c_opt, s_opt, mean_loss

    return epoch


class SplitNNAPI:
    """Relay-ring split learning (reference SplitNNAPI.py:15-39).

    Per the reference protocol each client holds ITS OWN lower-stage weights
    (they are never aggregated — only the server stage accumulates across
    clients) and trains ``epochs`` epochs when it holds the token.
    """

    def __init__(
        self,
        dataset: FedDataset,
        config: FedConfig,
        client_bundle: ModelBundle,
        server_bundle: ModelBundle,
    ):
        self.dataset = dataset
        self.config = config
        self.client_bundle = client_bundle
        self.server_bundle = server_bundle
        self.task = get_task(dataset.task, dataset.class_num)
        self.root_key = seed_everything(config.seed)

        # reference optimizers: SGD lr .1 momentum .9 wd 5e-4 for BOTH stages
        # (split_nn/client.py:18-19, server.py:19-20); ours come from config.
        self.tx_client = make_optimizer(config.client_optimizer, config.lr, config.momentum, config.wd)
        self.tx_server = make_optimizer(config.client_optimizer, config.lr, config.momentum, config.wd)

        n_clients = dataset.num_clients
        keys = jax.random.split(self.root_key, n_clients + 1)
        self.client_vars = [self.client_bundle.init(keys[i]) for i in range(n_clients)]
        self.server_vars = self.server_bundle.init(keys[-1])
        self.client_opts = [self.tx_client.init(v["params"]) for v in self.client_vars]
        self.server_opt = self.tx_server.init(self.server_vars["params"])

        self._epoch = make_splitnn_epoch_fn(
            client_bundle, server_bundle, self.task,
            self.tx_client, self.tx_server, config.batch_size,
        )
        self.history: dict[str, list] = {"epoch_loss": [], "val_acc": []}

    def _eval_client(self, k: int) -> float:
        """Server-side validation through client k's stage on the global test
        pool (reference validates whenever a client finishes its turn,
        server.py:62-70)."""
        x, y, m = self.dataset.test_x, self.dataset.test_y, self.dataset.test_mask
        acts = self.client_bundle.apply_eval(self.client_vars[k], x)
        logits = self.server_bundle.apply_eval(self.server_vars, acts)
        metrics = self.task.metrics(logits, y, m)
        return float(metrics["correct"]) / max(float(metrics["count"]), 1.0)

    def train(self) -> dict:
        c = self.config
        n_clients = self.dataset.num_clients
        for r in range(c.comm_round):
            rk = round_key(self.root_key, r)
            # relay ring: client 0 -> 1 -> ... -> n-1 (semaphore protocol,
            # client_manager.py:29-52), each training its local epochs
            for k in range(n_clients):
                x, y, m, count = self.dataset.client_slice_cached(k)
                cv, co = self.client_vars[k], self.client_opts[k]
                for e in range(c.epochs):
                    ekey = jax.random.fold_in(jax.random.fold_in(rk, k), e)
                    cv, self.server_vars, co, self.server_opt, loss = self._epoch(
                        cv, self.server_vars, co, self.server_opt,
                        x[0], y[0], m[0], jnp.float32(count[0]), ekey,
                    )
                self.client_vars[k], self.client_opts[k] = cv, co
                self.history["epoch_loss"].append(float(loss))
            self.history["val_acc"].append(self._eval_client(n_clients - 1))
            log.info("splitnn round %d val_acc %.4f", r, self.history["val_acc"][-1])
        return self.history
