"""FedNova — normalized averaging for heterogeneous local work
(Wang et al. 2020).

Counterpart of reference fedml_api/standalone/fednova/: a custom torch
Optimizer accumulates a per-client normalizing coefficient a_i as it steps
(fednova.py:10-155), and the trainer aggregates with an effective step count
tau_eff (fednova_trainer.py:97-124). Here the same math is computed in closed
form from the step count tau_i reported by the jitted local trainer
(LocalResult.tau) — no custom optimizer needed:

    a_i      = tau_i                                   (plain SGD)
             = (tau_i - rho*(1-rho^tau_i)/(1-rho)) / (1-rho)   (momentum rho)
    d_i      = (w_global - w_i) / a_i        normalized update direction
    tau_eff  = sum_i p_i a_i                 p_i = n_i / n_total
    w_next   = w_global - tau_eff * sum_i p_i d_i

With homogeneous tau and no momentum this reduces exactly to FedAvg (the
property the correctness test asserts).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import CrossSiloFedAvgAPI, FedAvgAPI
from fedml_tpu.parallel.local import LocalResult


def _nova_a(tau: jax.Array, rho: float) -> jax.Array:
    """FedNova's per-client normalizing coefficient a_i from step count tau_i
    (closed form of the reference optimizer's accumulation, fednova.py:10-155)."""
    if rho > 0.0:
        return (tau - rho * (1.0 - jnp.power(rho, tau)) / (1.0 - rho)) / (1.0 - rho)
    return tau


class FedNovaAPI(FedAvgAPI):
    def aggregate(self, variables, stacked_vars, counts, infos: LocalResult, rng, server_state):
        rho = float(self.config.momentum)
        tau = infos.tau.astype(jnp.float32)  # [C]
        a = _nova_a(tau, rho)
        p = counts.astype(jnp.float32)
        p = p / jnp.maximum(jnp.sum(p), 1e-12)
        tau_eff = jnp.sum(p * a)

        coef = (tau_eff * p / jnp.maximum(a, 1e-12))  # [C]

        def combine(g, stacked_local):
            # g - tau_eff * sum_i p_i (g - w_i)/a_i, computed leafwise
            cb = coef.reshape((-1,) + (1,) * (stacked_local.ndim - 1))
            delta = jnp.sum((g[None] - stacked_local.astype(jnp.float32)) * cb, axis=0)
            return (g - delta).astype(stacked_local.dtype)

        new_params = jax.tree.map(
            lambda g, s: combine(g.astype(jnp.float32), s),
            variables["params"], stacked_vars["params"],
        )
        # Non-param collections (BN stats): plain weighted average.
        from fedml_tpu.core.pytree import tree_weighted_mean

        new_vars = tree_weighted_mean(stacked_vars, counts)
        new_vars = dict(new_vars)
        new_vars["params"] = new_params
        return new_vars, server_state

    def crosssilo_hooks(self):
        """The hook decomposition of :meth:`aggregate` into weighted
        partial sums — on the BASE class because it is the aggregation
        contract of both non-vmap execution forms (the mesh psum tail AND
        the packed lane schedule's simulation round,
        FedAvgAPI._packing_hooks):

            pd = sum_i (n_i / a_i) (w_global - w_i)     (leafwise)
            na = sum_i  n_i * a_i                       (scalar)
            w_next = w_global - na * pd / n_total^2

        which equals the simulation form  w - tau_eff * sum_i p_i d_i
        with tau_eff = na/n_total and p_i = n_i/n_total — the reference
        runs this as a rank-0 aggregation over MPI-gathered state dicts
        (standalone/fednova/fednova_trainer.py:97-124)."""
        rho = float(self.config.momentum)

        def reduce_extras(gvars, res, w):
            a = _nova_a(res.tau.astype(jnp.float32), rho)
            inv = w / jnp.maximum(a, 1e-12)  # n_i / a_i  [local clients]

            def pd_leaf(g, s):
                cb = inv.reshape((-1,) + (1,) * (s.ndim - 1))
                return jnp.sum((g[None].astype(jnp.float32)
                                - s.astype(jnp.float32)) * cb, axis=0)

            pd = jax.tree.map(pd_leaf, gvars["params"], res.variables["params"])
            return {"pd": pd, "na": jnp.sum(w * a)}

        def server_update(vars0, agg, extras, total, server_state, rng):
            den2 = jnp.square(jnp.maximum(total, 1e-12))

            def combine(g, d):
                return (g.astype(jnp.float32) - extras["na"] * d / den2).astype(g.dtype)

            new_vars = dict(agg)  # non-param collections: weighted average
            new_vars["params"] = jax.tree.map(combine, vars0["params"], extras["pd"])
            return new_vars, server_state

        return dict(reduce_extras=reduce_extras, server_update=server_update)


class CrossSiloFedNovaAPI(CrossSiloFedAvgAPI, FedNovaAPI):
    """FedNova on the cross-silo mesh path: the partial sums from
    FedNovaAPI.crosssilo_hooks ride the same all-reduce as the parameters
    — one psum, no server rank."""
