"""FedNova — normalized averaging for heterogeneous local work
(Wang et al. 2020).

Counterpart of reference fedml_api/standalone/fednova/: a custom torch
Optimizer accumulates a per-client normalizing coefficient a_i as it steps
(fednova.py:10-155), and the trainer aggregates with an effective step count
tau_eff (fednova_trainer.py:97-124). Here the same math is computed in closed
form from the step count tau_i reported by the jitted local trainer
(LocalResult.tau) — no custom optimizer needed:

    a_i      = tau_i                                   (plain SGD)
             = (tau_i - rho*(1-rho^tau_i)/(1-rho)) / (1-rho)   (momentum rho)
    d_i      = (w_global - w_i) / a_i        normalized update direction
    tau_eff  = sum_i p_i a_i                 p_i = n_i / n_total
    w_next   = w_global - tau_eff * sum_i p_i d_i

With homogeneous tau and no momentum this reduces exactly to FedAvg (the
property the correctness test asserts).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.parallel.local import LocalResult


class FedNovaAPI(FedAvgAPI):
    def aggregate(self, variables, stacked_vars, counts, infos: LocalResult, rng, server_state):
        rho = float(self.config.momentum)
        tau = infos.tau.astype(jnp.float32)  # [C]
        if rho > 0.0:
            a = (tau - rho * (1.0 - jnp.power(rho, tau)) / (1.0 - rho)) / (1.0 - rho)
        else:
            a = tau
        p = counts.astype(jnp.float32)
        p = p / jnp.maximum(jnp.sum(p), 1e-12)
        tau_eff = jnp.sum(p * a)

        coef = (tau_eff * p / jnp.maximum(a, 1e-12))  # [C]

        def combine(g, stacked_local):
            # g - tau_eff * sum_i p_i (g - w_i)/a_i, computed leafwise
            cb = coef.reshape((-1,) + (1,) * (stacked_local.ndim - 1))
            delta = jnp.sum((g[None] - stacked_local.astype(jnp.float32)) * cb, axis=0)
            return (g - delta).astype(stacked_local.dtype)

        new_params = jax.tree.map(
            lambda g, s: combine(g.astype(jnp.float32), s),
            variables["params"], stacked_vars["params"],
        )
        # Non-param collections (BN stats): plain weighted average.
        from fedml_tpu.core.pytree import tree_weighted_mean

        new_vars = tree_weighted_mean(stacked_vars, counts)
        new_vars = dict(new_vars)
        new_vars["params"] = new_params
        return new_vars, server_state
