"""FedAvg-robust — backdoor attack simulation + robust aggregation defenses.

Counterpart of reference fedml_api/distributed/fedavg_robust/: client rank 1
is a backdoor attacker training on poisoned data (FedAvgRobustTrainer.py:14-25,
poisoned datasets from edge_case_examples/data_loader.py:283), the server
defends with norm-difference clipping and weak-DP gaussian noise
(FedAvgRobustAggregator.py:14-60 + robustness/robust_aggregation.py:38-55),
and evaluation tracks the targeted backdoor success rate alongside main-task
accuracy.

Attack model here: pixel-trigger backdoor — the attacker stamps a trigger
patch on its samples and relabels them to ``target_class``; backdoor success
= fraction of triggered test inputs classified as the target.

Detection counterpart: the fedlens telemetry (``--lens on``, obs/lens.py)
scores every client's RAW update — pre-``client_transform``, so the clip
defense here cannot hide the attacker from its own server's telemetry —
and the watchdog's ``aligned_suspects`` rule names the anti-aligned
high-norm client ids. The e2e pin (tests/test_lens.py) runs exactly this
attacker through an armed federation and asserts ``attacker_idx`` tops the
suspect list.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from fedml_tpu.algorithms.fedavg import CrossSiloFedAvgAPI, FedAvgAPI
from fedml_tpu.core.aggregation import (
    add_dp_noise,
    clip_update_by_norm,
    robust_aggregate,
)
from fedml_tpu.parallel.local import LocalResult, finalize_metrics


def stamp_trigger(x: np.ndarray, value: float = 2.5, size: int = 3) -> np.ndarray:
    """Stamp a bright square in the top-left corner (image tensors [..., H, W, C]
    or flat vectors — flat vectors get their first ``size*size`` features set)."""
    x = np.array(x, copy=True)
    if x.ndim >= 3:
        x[..., :size, :size, :] = value
    else:
        x[..., : size * size] = value
    return x


class FedAvgRobustAPI(FedAvgAPI):
    """FedAvg with one backdoor attacker and clip/DP server defenses."""

    def __init__(self, dataset, config, bundle=None,
                 attacker_idx: int = 0, target_class: int = 1,
                 poison_frac: Optional[float] = None,
                 trigger_value: float = 2.5, trigger_size: int = 3):
        poison_frac = config.poison_frac if poison_frac is None else poison_frac
        self.trigger_value = trigger_value
        self.trigger_size = trigger_size
        if poison_frac > 0:
            dataset = self._poison(dataset, attacker_idx, target_class,
                                   poison_frac, trigger_value, trigger_size)
        self.attacker_idx = attacker_idx
        self.target_class = target_class
        super().__init__(dataset, config, bundle)

    @staticmethod
    def _poison(dataset, attacker_idx: int, target_class: int, frac: float,
                trigger_value: float = 2.5, trigger_size: int = 3):
        import dataclasses

        tx = np.array(dataset.train_x, copy=True)
        ty = np.array(dataset.train_y, copy=True)
        # fraction of the attacker's REAL records (real rows come first in
        # the padded layout), not of the padded length
        n_real = int(dataset.train_mask[attacker_idx].sum())
        n_poison = int(n_real * frac)
        tx[attacker_idx, :n_poison] = stamp_trigger(
            tx[attacker_idx, :n_poison], trigger_value, trigger_size)
        ty[attacker_idx, :n_poison] = target_class
        return dataclasses.replace(dataset, train_x=tx, train_y=ty)

    def aggregate(self, variables, stacked_vars, counts, infos: LocalResult, rng, server_state):
        c = self.config
        agg = robust_aggregate(
            variables, stacked_vars, counts,
            norm_bound=c.norm_bound, dp_stddev=c.stddev, rng=rng,
        )
        return agg, server_state

    def crosssilo_hooks(self):
        """Mesh-path split of robust_aggregate: the norm-difference clip is
        per-client (pre-psum, on each silo's device); the weak-DP gaussian
        noise is added to the replicated aggregate post-psum with the same
        server key (``rng.server_key`` of the round key) on every device, so
        the result is identical to the reference's rank-0 defense
        (FedAvgRobustAggregator.py:14-60) and to the simulation paradigm's
        aggregate()."""
        c = self.config
        norm_bound, stddev = c.norm_bound, c.stddev

        def client_transform(gvars, stacked):
            if norm_bound is None:
                return stacked
            return jax.vmap(
                lambda local: clip_update_by_norm(gvars, local, norm_bound)
            )(stacked)

        def server_update(vars0, agg, extras, total, server_state, rng):
            if stddev is not None:
                agg = add_dp_noise(agg, stddev, rng)
            return agg, server_state

        return dict(client_transform=client_transform, server_update=server_update)

    def evaluate_backdoor(self) -> dict:
        """Targeted-class success on triggered test inputs (reference
        FedAvgRobustAggregator's backdoor eval on the targeted task)."""
        ds = self.dataset
        keep = ds.test_y != self.target_class  # non-target samples only
        x = stamp_trigger(np.asarray(ds.test_x)[keep],
                          self.trigger_value, self.trigger_size)
        y = np.full(x.shape[0], self.target_class, ds.test_y.dtype)
        m = np.asarray(ds.test_mask)[keep]
        # the jitted eval ceil-pads internally, no host-side padding needed
        sums = self._eval(self.variables, x, y, m)
        out = finalize_metrics(jax.tree.map(np.asarray, sums))
        return {"backdoor_success": out.get("acc", 0.0)}


class CrossSiloFedAvgRobustAPI(CrossSiloFedAvgAPI, FedAvgRobustAPI):
    """FedAvg-robust on the cross-silo mesh path: clip per-silo pre-psum,
    DP-noise the replicated aggregate post-psum (hooks defined on
    FedAvgRobustAPI.crosssilo_hooks). The attacker is just one of the
    sharded silos; the backdoor eval is unchanged."""
