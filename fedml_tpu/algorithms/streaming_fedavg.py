"""Streaming FedAvg — federated rounds for datasets exceeding the device
budget (VERDICT r2 #6).

The in-memory paradigm (FedAvgAPI) holds the stacked federation in HBM and
trains the cohort as one vmapped program. At ImageNet/Landmarks scale that
stack does not fit; the reference streams every dataset through DataLoader
worker processes instead (cifar10/data_loader.py:160-233). This is the
TPU-native counterpart: client records stay HOST-resident, the native
threaded pipeline (fedml_tpu/native.HostPipeline, C++ workers) assembles
shuffled batches off-GIL into a bounded ring, `device_stream` keeps
transfers in flight ahead of the consumer, and the device runs one jitted
per-batch SGD step — host batch assembly, host->device transfer, and device
compute all overlap; host memory is bounded by the pipeline ring
(depth x batch), device memory by one client's working set.

Numerical parity with the in-memory path is EXACT by construction, not
approximate: the pipeline runs in explicit-order mode with the same
per-epoch shuffle the jitted scan derives (perm = random.permutation(ekey),
real-records-first stable sort; batch keys split(fold_in(ekey, 0x5ba7)) —
see parallel/local.make_local_train_fn), and the in-memory path's masked
padding steps are no-ops (live=0 freezes params/opt/stats and zeroes the
loss), so streaming ONLY the real batches reproduces the identical update
sequence. tests/test_streaming_fedavg.py pins rounds equal to FedAvgAPI.
"""

from __future__ import annotations

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.rng import round_key
from fedml_tpu.parallel.local import LocalResult

log = logging.getLogger(__name__)

# must match parallel/local.make_local_train_fn's batch-key derivation
_BATCH_KEY_TAG = 0x5BA7


class StreamingFedAvgAPI(FedAvgAPI):
    """FedAvg whose clients stream host-resident batches through the native
    pipeline; cohort clients train sequentially on the device (the price of
    not fitting in HBM), aggregation and the elastic-round guard are the
    shared ``_finish_round``."""

    supports_device_data = False  # the point is that data does NOT go resident
    elastic_rounds_ok = True      # zero-weight failures via _finish_round

    def __init__(self, dataset, config, bundle=None, n_threads: int = 2,
                 depth: int = 4):
        self.n_threads, self.depth = n_threads, depth
        super().__init__(dataset, config, bundle)
        self._batch_step = self._build_batch_step()
        self._opt_init = jax.jit(lambda p: self._opt_tx.init(p))
        self._finish_jit = jax.jit(self._finish_round)
        self._stream_fold = None

    def _stream_mode(self) -> str:
        """This paradigm HAS no single round program to mirror — the base
        gate's build_round_step check doesn't apply. Streaming folds the
        plain weighted mean, so only a custom aggregate() opts out."""
        memo = self._stream_mode_memo
        if memo is not None:
            return memo
        mode = self.config.stream_aggregate
        if mode != "off" and type(self).aggregate is not FedAvgAPI.aggregate:
            log.warning(
                "stream_aggregate=%r ignored: %s overrides aggregate(), "
                "which the streaming fold cannot mirror", mode,
                type(self).__name__)
            mode = "off"
        self._stream_mode_memo = mode
        return mode

    def build_round_step(self):
        # rounds are driven batch-by-batch in run_round; there is no single
        # whole-round XLA program to build on this paradigm
        return None

    def _build_batch_step(self):
        from fedml_tpu.parallel.local import make_batch_sgd_step, make_optimizer

        c = self.config
        tx = make_optimizer(c.client_optimizer, c.lr, c.momentum, c.wd)
        self._opt_tx = tx
        # the SAME per-batch step make_local_train_fn scans — shared
        # definition, so the streaming path cannot drift from the in-memory
        # one (params0 threaded for FedProx-style subclasses)
        step = make_batch_sgd_step(
            self.bundle, self.task, tx, grad_clip=c.grad_clip,
            compute_dtype=jnp.bfloat16 if c.dtype == "bfloat16" else None,
        )
        return jax.jit(step)

    def _client_orders(self, mask, count, rng):
        """The jitted scan's exact per-epoch order, truncated to the real
        batches: perm(ekey) stable-sorted real-first; only the first
        ceil(count/bs) batches carry live steps (the rest are frozen no-ops
        in the in-memory path), so only they are streamed."""
        c = self.config
        n_pad = mask.shape[0]
        bs = c.batch_size
        steps_real = int(np.ceil(max(float(count), 1.0) / bs))
        mask_d = jnp.asarray(mask)
        ekeys = jax.random.split(rng, c.epochs)
        orders = []
        for e in range(c.epochs):
            perm = jax.random.permutation(ekeys[e], n_pad)
            order = perm[jnp.argsort(-mask_d[perm], stable=True)]
            orders.append(np.asarray(order[: steps_real * bs]))
        return np.stack(orders), ekeys, steps_real

    def _prefetch_build(self, round_idx: int, pool):
        """Streaming rides the host round pipeline with a HOST payload: the
        materialized per-client arrays, no trim/cast/device_put — the
        per-batch stream ships records to the device batch-by-batch as
        today. Only the materialization moves off the round's critical
        path, and it goes through the SAME client_slice_cached LRU the
        serial client_arrays path uses — live clients only, cross-round
        repeats served from cache — so the work done (and a cross-device
        dataset's materialized_rows) is identical to the serial path by
        construction. Payload maps cohort position -> (x, y, mask)."""
        t0 = time.perf_counter()
        sampled, live, _bucket = self._round_plan(round_idx)
        keep = [int(p) for p in (range(len(sampled)) if live is None
                                 else np.flatnonzero(live > 0))]
        ids = [int(sampled[p]) for p in keep]
        # cap covers the pipeline's steady-state working set (depth + 1
        # cohorts), so in-flight rounds cannot evict each other's clients
        cap = max(64, len(sampled) * (self.config.host_pipeline_depth + 1))

        def fetch(k):
            return self.dataset.client_slice_cached(k, cap=cap)

        parts = (list(pool.map(fetch, ids)) if pool is not None
                 else [fetch(k) for k in ids])
        rows = {p: (x[0], y[0], m[0])
                for p, (x, y, m, _c) in zip(keep, parts)}
        return rows, {
            "materialize_ms": (time.perf_counter() - t0) * 1e3,
            "h2d_ms": 0.0}

    def _train_client_streaming(self, k: int, rng, data=None):
        """One client's local run: ordered native pipeline over its host
        slice + the per-batch jitted step. ``data`` = prefetched (x, y,
        mask) host arrays from the round pipeline; None materializes on
        demand. Returns (variables, last-epoch mean loss, tau)."""
        from fedml_tpu.data.pipeline import HostPipeline, device_stream

        c = self.config
        bs = c.batch_size
        # one client's host arrays: a view for stacked datasets, an
        # O(1-client) materialization for virtual cross-device ones
        x, y, mask = data if data is not None else self.dataset.client_arrays(int(k))
        x, y = np.asarray(x), np.asarray(y)
        mask = np.asarray(mask)
        count = float(self.dataset.train_counts[k])
        orders, ekeys, steps_real = self._client_orders(mask, count, rng)
        n_pad = mask.shape[0]
        steps_full = n_pad // bs

        variables = self.variables
        params0 = variables["params"]
        opt_state = self._opt_init(params0)
        pipe = HostPipeline(x, None, bs, n_threads=self.n_threads,
                            depth=self.depth, orders=orders)
        try:
            stream = device_stream(pipe, n_batches=c.epochs * steps_real)
            for e in range(c.epochs):
                bkeys = jax.random.split(
                    jax.random.fold_in(ekeys[e], _BATCH_KEY_TAG), steps_full)
                # labels/mask are tiny next to x: stage the whole epoch's
                # once so the hot loop has no per-step host->device hops
                # beyond the prefetched x stream
                by_e = jnp.asarray(y[orders[e]]).reshape((steps_real, bs)
                                                         + y.shape[1:])
                bm_e = jnp.asarray(mask[orders[e]], jnp.float32).reshape(
                    (steps_real, bs))
                ep_loss = jnp.zeros(())
                for s in range(steps_real):
                    bx, _ = next(stream)
                    variables, opt_state, l = self._batch_step(
                        variables, opt_state, params0, bx, by_e[s], bm_e[s],
                        bkeys[s])
                    ep_loss = ep_loss + l
                last_loss = ep_loss / max(steps_real, 1)
        finally:
            pipe.close()
        tau = jnp.float32(c.epochs * steps_real)
        return variables, last_loss, tau

    def _build_stream_fold(self):
        """Device fold for --stream_aggregate: one client's result folds
        into the running f32 accumulator (normalize-first weights — the
        round total is known from the plan), so the round holds ONE
        model-shaped sum instead of the O(cohort) stacked list."""
        @jax.jit
        def fold(acc, acc_loss, v, loss, w_norm, w):
            acc = jax.tree.map(
                lambda a, x: a + x.astype(jnp.float32) * w_norm, acc, v)
            return acc, acc_loss + loss * w

        return fold

    def _run_round_streamed(self, round_idx, sampled, counts, keys, cohort):
        """The sequential client loop with the streaming fold (O(1) server
        memory); aggregation mirrors _finish_round's arithmetic at the
        fedseg tolerance (per-client fold order vs one stacked sum)."""
        if self._stream_fold is None:
            self._stream_fold = self._build_stream_fold()
        acc = jax.tree.map(lambda v: jnp.zeros(v.shape, jnp.float32),
                           self.variables)
        acc_loss = jnp.zeros(())
        total = np.float32(counts.sum())
        denom = np.maximum(total, np.float32(1e-12))
        for i, k in enumerate(sampled):
            if counts[i] <= 0:
                continue   # zero weight: its term in the mean is exactly 0
            data = None if cohort is None else cohort[i]
            v, l, _tau = self._train_client_streaming(int(k), keys[i], data)
            acc, acc_loss = self._stream_fold(
                acc, acc_loss, v, l,
                jnp.float32(counts[i] / denom), jnp.float32(counts[i]))
        keep = total > 0
        if keep:
            self.variables = jax.tree.map(
                lambda a, v: a.astype(v.dtype), acc, self.variables)
        self.stream_stats = {
            "mode": self.config.stream_aggregate, "cohort": len(sampled),
            "chunks": len(sampled),
            "accumulator_bytes": int(sum(
                int(np.prod(v.shape)) * 4
                for v in jax.tree.leaves(self.variables)) + 8)}
        return acc_loss / jnp.maximum(jnp.float32(total), 1e-12)

    def _run_round_inner(self, round_idx: int):
        # traced via the base run_round wrapper (one "round" span per round)
        sampled, live, _bucket = self._round_plan(round_idx, record=True)
        rk = round_key(self.root_key, round_idx)
        keys = jax.random.split(rk, len(sampled))
        outs, losses, taus = [], [], []
        counts = np.asarray(self.dataset.train_counts, np.float32)[sampled]
        if live is not None:
            counts = counts * live
        pf = self._host_prefetcher()
        cohort = stages = None
        wait_ms = 0.0
        if pf is not None:
            cohort, stages, wait_ms = pf.pop(round_idx)
        t0 = time.perf_counter()
        streamed = None
        if self._stream_mode() != "off":
            streamed = self._run_round_streamed(
                round_idx, sampled, counts, keys, cohort)
        else:
            for i, k in enumerate(sampled):
                if counts[i] <= 0:
                    # failed client: zero aggregation weight — its (skipped)
                    # training result cannot influence the round, so train a
                    # placeholder from the current globals for tree shape only
                    outs.append(self.variables)
                    losses.append(jnp.zeros(()))
                    taus.append(jnp.zeros(()))
                    continue
                # prefetched rows exist exactly for live positions (the
                # counts[i] > 0 guard above matches the build's live filter)
                data = None if cohort is None else cohort[i]
                v, l, tau = self._train_client_streaming(int(k), keys[i], data)
                outs.append(v)
                losses.append(l)
                taus.append(tau)
        if stages is not None:
            row = dict(stages, wait_ms=wait_ms, round=round_idx,
                       compute_ms=(time.perf_counter() - t0) * 1e3)
            self._stage_rows.append(row)
            from fedml_tpu.obs import default_registry

            default_registry().append_row("stage", row)
        if streamed is not None:
            return (streamed if self.config.async_rounds
                    else float(streamed))
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *outs)
        res = LocalResult(stacked, jnp.stack(losses), jnp.stack(taus))
        out = self._finish_jit(
            self.variables, self.server_state, res,
            jnp.asarray(counts, jnp.float32), rk)
        # fedlens rides the shared _finish_round (norm + align; no
        # loss_delta — the sequential trainer reports one mean loss)
        self.variables, self.server_state, train_loss = self._lens_absorb(
            round_idx, out, np.asarray(sampled, np.int64), counts > 0)
        return train_loss if self.config.async_rounds else float(train_loss)
