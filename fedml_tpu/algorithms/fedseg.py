"""FedSeg — federated semantic segmentation.

Counterpart of reference fedml_api/distributed/fedseg/ (FedSegAggregator.py:
12-190): FedAvg weight aggregation over a segmentation model, with the
Evaluator's confusion-matrix metrics (Acc / Acc_class / mIoU / FWIoU,
utils.py:246-283) tracked per round in an EvaluationMetricsKeeper-style
history (utils.py:62-70).

The round loop, vmapped local trainer, and psum aggregation are inherited
from FedAvgAPI — the segmentation task's loss/metrics (core/tasks.py)
carry the confusion matrix through the same jitted eval scan, so the only
specialization here is score finalization."""

from __future__ import annotations

import logging

import jax
import numpy as np

from fedml_tpu.algorithms.fedavg import CrossSiloFedAvgAPI, FedAvgAPI
from fedml_tpu.core.tasks import segmentation_scores

log = logging.getLogger(__name__)


class FedSegAPI(FedAvgAPI):
    """Standalone-simulation federated segmentation."""

    def evaluate_global(self) -> dict:
        sums = jax.device_get(self._eval(
            self.variables, self.dataset.test_x, self.dataset.test_y,
            self.dataset.test_mask,
        ))
        scores = {k: float(v) for k, v in segmentation_scores(sums["confusion"]).items()}
        # FedAvgAPI.train logs 'acc'/'loss'; map pixel-acc and mIoU onto them
        scores["acc"] = scores["Acc"]
        scores["loss"] = 1.0 - scores["mIoU"]
        scores["confusion_total"] = float(np.sum(np.asarray(sums["confusion"])))
        return scores


class CrossSiloFedSegAPI(CrossSiloFedAvgAPI, FedSegAPI):
    """FedSeg on the cross-silo mesh path — the deployable counterpart of
    the reference's distributed FedSeg (FedSegAggregator.py:12-190). Its
    aggregation is the plain weighted mean, so the in-mesh psum round is
    inherited unchanged from CrossSiloFedAvgAPI; FedSegAPI contributes the
    confusion-matrix mIoU/FWIoU evaluation on the replicated result."""
