"""Classical vertical federated learning (feature-partitioned parties).

Counterpart of reference fedml_api/standalone/classical_vertical_fl/:
``VerticalMultiplePartyLogisticRegressionFederatedLearning.fit`` (vfl.py:21-50)
runs, per batch: hosts send logit components, the guest sums them with its
own, computes BCE loss and the COMMON GRADIENT dL/dU (party_models.py:57-69),
sends it back, and every party backprops its local stack from that gradient.
Party stacks mirror finance/vfl_models_standalone.py: local layer =
Linear+LeakyReLU, head = Linear to 1 logit (bias only on the guest), each
party an SGD(momentum=0.9, wd=0.01) optimizer.

Three executions of the same math, sharing one init:

1. **fused** — the TPU-first path: the whole multi-party step is ONE jitted
   program; ``jax.grad`` through the summed logit IS the common-gradient
   relay (autodiff computes dL/dU once and routes it to every party's
   subtree), so no wire and no Python protocol remain.
2. **sharded** — same step under ``shard_map`` over a "party" mesh axis with
   dim-padded parties and a ``psum`` of logit contributions: the SPMD
   feature-sharded form (SURVEY.md §2.6.4) that scales parties across chips.
3. **protocol** — explicit Guest/Host party objects exchanging components
   and the common gradient, for the genuinely-distributed edge deployment
   (and as the executable spec the fused forms are tested against).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fedml_tpu.data.vertical import VerticalDataset


def _party_optimizer(lr: float) -> optax.GradientTransformation:
    # torch.optim.SGD(momentum=0.9, weight_decay=0.01) semantics
    # (vfl_models_standalone.py:13,46)
    return optax.chain(optax.add_decayed_weights(0.01), optax.sgd(lr, momentum=0.9))


def init_party_params(
    rng: jax.Array, input_dim: int, hidden_dim: int, guest: bool
) -> dict:
    k1, k2, k3 = jax.random.split(rng, 3)
    lim1 = 1.0 / np.sqrt(input_dim)
    lim2 = 1.0 / np.sqrt(hidden_dim)
    p = {
        "local_w": jax.random.uniform(k1, (input_dim, hidden_dim), minval=-lim1, maxval=lim1),
        "local_b": jnp.zeros((hidden_dim,)),
        "head_w": jax.random.uniform(k2, (hidden_dim, 1), minval=-lim2, maxval=lim2),
    }
    if guest:
        p["head_b"] = jnp.zeros((1,))
    return p


def party_component(params: dict, x: jax.Array) -> jax.Array:
    """One party's logit contribution U_p [B, 1]."""
    z = jax.nn.leaky_relu(x @ params["local_w"] + params["local_b"])
    u = z @ params["head_w"]
    if "head_b" in params:
        u = u + params["head_b"]
    return u


def bce_with_logits(u: jax.Array, y: jax.Array) -> jax.Array:
    l = u.astype(jnp.float32)
    t = y.astype(jnp.float32)
    return jnp.mean(jnp.maximum(l, 0.0) - l * t + jnp.log1p(jnp.exp(-jnp.abs(l))))


class VFLAPI:
    """Fused standalone VFL (execution 1); ``use_mesh_sharding`` switches the
    step to the shard_map form (execution 2) when a party-axis mesh is
    available."""

    def __init__(
        self,
        dataset: VerticalDataset,
        hidden_dim: int = 16,
        lr: float = 0.01,
        batch_size: int = 64,
        seed: int = 0,
    ):
        self.dataset = dataset
        self.hidden = hidden_dim
        self.lr = lr
        self.batch_size = batch_size
        root = jax.random.PRNGKey(seed)
        keys = jax.random.split(root, dataset.num_parties)
        self.params = [
            init_party_params(keys[p], d, hidden_dim, guest=(p == 0))
            for p, d in enumerate(dataset.party_dims)
        ]
        self._tx = _party_optimizer(lr)
        self.opt_states = [self._tx.init(p) for p in self.params]
        self._step = self._build_step()
        self.history: list[dict] = []

    def _build_step(self):
        tx = self._tx

        @jax.jit
        def step(params_list, opt_list, xs, y):
            def loss_fn(plist):
                u = sum(party_component(p, x) for p, x in zip(plist, xs))
                return bce_with_logits(u[:, 0], y)

            loss, grads = jax.value_and_grad(loss_fn)(params_list)
            new_params, new_opts = [], []
            for p, o, g in zip(params_list, opt_list, grads):
                upd, no = tx.update(g, o, p)
                new_params.append(optax.apply_updates(p, upd))
                new_opts.append(no)
            return new_params, new_opts, loss

        return step

    def fit(self, epochs: int = 10, seed: int = 0) -> dict:
        d = self.dataset
        n = len(d.train_y)
        bs = min(self.batch_size, n)
        steps = n // bs
        rng = np.random.default_rng(seed)
        xs_all = [jnp.asarray(p) for p in d.train_parts]
        y_all = jnp.asarray(d.train_y)
        last = {}
        for ep in range(epochs):
            order = rng.permutation(n)[: steps * bs].reshape(steps, bs)
            losses = []
            for b in range(steps):
                idx = jnp.asarray(order[b])
                xs = [x[idx] for x in xs_all]
                self.params, self.opt_states, loss = self._step(
                    self.params, self.opt_states, xs, y_all[idx]
                )
                losses.append(float(loss))
            last = {"epoch": ep, "Train/Loss": float(np.mean(losses)), **self.evaluate()}
            self.history.append(last)
        return last

    def predict_logits(self, parts: Sequence[np.ndarray]) -> np.ndarray:
        u = sum(
            party_component(p, jnp.asarray(x)) for p, x in zip(self.params, parts)
        )
        return np.asarray(u[:, 0])

    def evaluate(self) -> dict:
        d = self.dataset
        u = self.predict_logits(d.test_parts)
        pred = (u > 0).astype(np.float32)
        return {
            "Test/Acc": float((pred == d.test_y).mean()),
            "Test/Loss": float(bce_with_logits(jnp.asarray(u), jnp.asarray(d.test_y))),
        }


# --------------------------------------------------------------------------
# Execution 2: SPMD feature-sharded step over a "party" mesh axis.
# --------------------------------------------------------------------------

def pad_party_params(params_list: list[dict], party_dims: Sequence[int]) -> dict:
    """Stack per-party params into one pytree [P, ...] with input dims
    zero-padded to max(party_dims); guest bias becomes a masked row."""
    P = len(params_list)
    d_max = max(party_dims)
    hid = params_list[0]["local_w"].shape[1]
    local_w = jnp.zeros((P, d_max, hid))
    for p, prm in enumerate(params_list):
        local_w = local_w.at[p, : party_dims[p]].set(prm["local_w"])
    return {
        "local_w": local_w,
        "local_b": jnp.stack([p["local_b"] for p in params_list]),
        "head_w": jnp.stack([p["head_w"] for p in params_list]),
        "head_b": jnp.stack(
            [params_list[p].get("head_b", jnp.zeros((1,))) for p in range(P)]
        ),
        "head_b_mask": jnp.array([1.0] + [0.0] * (P - 1))[:, None],
    }


def make_sharded_vfl_step(mesh, lr: float, axis: str = "party"):
    """Build the shard_map step: each device holds one party's padded slice;
    the only cross-party communication is a psum of [B,1] logit
    contributions and the implicit psum of the common gradient on the
    backward pass — the reference's whole message protocol (vfl.py:30-48)
    becomes two ICI collectives."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    tx = _party_optimizer(lr)

    def per_party_loss(params, x, y):
        z = jax.nn.leaky_relu(x @ params["local_w"] + params["local_b"])
        # the mask is structural (guest-only bias), not a trainable leaf
        bias_mask = jax.lax.stop_gradient(params["head_b_mask"])
        u = z @ params["head_w"] + params["head_b"] * bias_mask
        u_total = jax.lax.psum(u, axis)            # [B,1] summed over parties
        return bce_with_logits(u_total[:, 0], y)

    def step(stacked_params, stacked_opt, xs_padded, y):
        # shard_map body: leading party axis is sharded away
        def body(params, opt, x, y):
            params = jax.tree.map(lambda a: a[0], params)
            opt = jax.tree.map(lambda a: a[0], opt)
            x = x[0]
            loss, grads = jax.value_and_grad(
                lambda p: per_party_loss(p, x, y)
            )(params)
            upd, new_opt = tx.update(grads, opt, params)
            # freeze the structural mask entirely (no grad, no weight decay)
            upd["head_b_mask"] = jnp.zeros_like(upd["head_b_mask"])
            new_params = optax.apply_updates(params, upd)
            one = lambda t: jax.tree.map(lambda a: a[None], t)
            return one(new_params), one(new_opt), loss[None]

        sharded = shard_map(
            body, mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P()),
            out_specs=(P(axis), P(axis), P(axis)),
        )
        new_params, new_opt, losses = sharded(stacked_params, stacked_opt, xs_padded, y)
        return new_params, new_opt, losses[0]

    return jax.jit(step), tx


# --------------------------------------------------------------------------
# Execution 3: explicit guest/host protocol objects (edge deployment).
# --------------------------------------------------------------------------

class VFLHostParty:
    """Host: no labels; sends logit components, learns from the common
    gradient (party_models.py:81-120)."""

    def __init__(self, params: dict, lr: float):
        self.params = params
        self._tx = _party_optimizer(lr)
        self.opt_state = self._tx.init(params)
        self._x = None

        @jax.jit
        def backward(params, opt_state, x, common_grad):
            def fwd(p):
                return party_component(p, x)
            _, vjp = jax.vjp(fwd, params)
            (grads,) = vjp(common_grad)
            upd, new_opt = self._tx.update(grads, opt_state, params)
            return optax.apply_updates(params, upd), new_opt

        self._backward = backward

    def set_batch(self, x: np.ndarray):
        self._x = jnp.asarray(x)

    def send_components(self) -> jax.Array:
        return party_component(self.params, self._x)

    def receive_gradients(self, common_grad: jax.Array):
        self.params, self.opt_state = self._backward(
            self.params, self.opt_state, self._x, common_grad
        )

    def predict(self, x: np.ndarray) -> jax.Array:
        return party_component(self.params, jnp.asarray(x))


class VFLGuestParty:
    """Guest: holds labels; fuses components, computes loss + common grad
    dL/dU, updates its own stack (party_models.py:12-78)."""

    def __init__(self, params: dict, lr: float):
        self.params = params
        self._tx = _party_optimizer(lr)
        self.opt_state = self._tx.init(params)
        self._x = self._y = None
        self._components: list[jax.Array] = []
        self.loss = None

        @jax.jit
        def fit_fn(params, opt_state, x, y, others_sum):
            def loss_fn(p):
                u = party_component(p, x) + others_sum
                return bce_with_logits(u[:, 0], y)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            # common gradient dL/dU — what every party backprops from
            u_total = party_component(params, x) + others_sum
            common = jax.grad(
                lambda u: bce_with_logits(u[:, 0], y)
            )(u_total)
            upd, new_opt = self._tx.update(grads, opt_state, params)
            return optax.apply_updates(params, upd), new_opt, loss, common

        self._fit = fit_fn

    def set_batch(self, x: np.ndarray, y: np.ndarray):
        self._x, self._y = jnp.asarray(x), jnp.asarray(y)

    def receive_components(self, component_list: Sequence[jax.Array]):
        self._components = list(component_list)

    def fit(self):
        others = sum(self._components) if self._components else 0.0
        self.params, self.opt_state, loss, self._common = self._fit(
            self.params, self.opt_state, self._x, self._y, others
        )
        self.loss = float(loss)
        self._components = []

    def send_gradients(self) -> jax.Array:
        return self._common

    def predict(self, x: np.ndarray, component_list: Sequence[jax.Array]) -> np.ndarray:
        u = party_component(self.params, jnp.asarray(x)) + sum(component_list)
        return np.asarray(jax.nn.sigmoid(u[:, 0]))


class VerticalFederatedLearning:
    """Coordinator mirroring reference vfl.py:21-55 fit/predict."""

    def __init__(self, guest: VFLGuestParty, hosts: dict):
        self.guest = guest
        self.hosts = dict(hosts)

    def fit(self, X_guest, y, host_X_dict, global_step: int = 0) -> float:
        if set(host_X_dict) != set(self.hosts):
            raise ValueError(
                f"host_X_dict must cover every host: have {sorted(self.hosts)}, "
                f"got {sorted(host_X_dict)}"
            )
        self.guest.set_batch(X_guest, y)
        for hid, x in host_X_dict.items():
            self.hosts[hid].set_batch(x)
        self.guest.receive_components(
            [h.send_components() for h in self.hosts.values()]
        )
        self.guest.fit()
        common = self.guest.send_gradients()
        for h in self.hosts.values():
            h.receive_gradients(common)
        return self.guest.loss

    def predict(self, X_guest, host_X_dict) -> np.ndarray:
        comps = [self.hosts[h].predict(x) for h, x in host_X_dict.items()]
        return self.guest.predict(X_guest, comps)


def build_protocol_vfl(
    dataset: VerticalDataset, hidden_dim: int = 16, lr: float = 0.01, seed: int = 0
) -> VerticalFederatedLearning:
    root = jax.random.PRNGKey(seed)
    keys = jax.random.split(root, dataset.num_parties)
    guest = VFLGuestParty(
        init_party_params(keys[0], dataset.party_dims[0], hidden_dim, guest=True), lr
    )
    hosts = {
        p: VFLHostParty(
            init_party_params(keys[p], dataset.party_dims[p], hidden_dim, guest=False), lr
        )
        for p in range(1, dataset.num_parties)
    }
    return VerticalFederatedLearning(guest, hosts)
