"""FedProx — local proximal regularization (Li et al. 2018).

The reference ADVERTISES FedProx (fedml_api/distributed/fedprox/) but its
trainer is byte-identical to FedAvg's — the proximal term was never
implemented (verified in SURVEY.md §2.2: MyModelTrainer.py:18-48 is plain
SGD/Adam). This implementation adds the real term: each local step minimizes

    F_k(w) + (mu/2) ||w - w_global||^2

which is exactly the ``prox_mu`` hook of the shared local trainer
(fedml_tpu/parallel/local.py) — the gradient gains mu*(w - w_global).
Aggregation is unchanged FedAvg.
"""

from __future__ import annotations

from fedml_tpu.algorithms.fedavg import CrossSiloFedAvgAPI, FedAvgAPI


class FedProxAPI(FedAvgAPI):
    def _local_train_kwargs(self) -> dict:
        # inject via the shared kwargs mapping (not build_local_train) so
        # EVERY trainer form — vmapped, grouped, the packed lanes AND the
        # fedpack joint MXU form (which folds the per-lane prox term into
        # its summed loss, parallel/packed.py) — carries the proximal term
        return dict(super()._local_train_kwargs(),
                    prox_mu=self.config.fedprox_mu)


class CrossSiloFedProxAPI(CrossSiloFedAvgAPI, FedProxAPI):
    """FedProx on the cross-silo mesh path: the proximal term is entirely
    client-side (build_local_train), aggregation is plain weighted psum —
    the MRO composes the two with no extra code (the reference would run
    this as its fedprox MPI deployment, which is FedAvg's)."""
