"""Decentralized (serverless) FL: DSGD and PushSum gossip over a topology.

Counterpart of reference fedml_api/standalone/decentralized/ (ClientDSGD
client_dsgd.py:6-90, ClientPushsum client_pushsum.py:7-108,
FedML_decentralized_fl decentralized_fl_api.py:20) and the MPI template
fedml_api/distributed/decentralized_framework/ (neighbor send
decentralized_worker_manager.py:41-46).

The reference exchanges per-neighbor messages; here one gossip round is a
single XLA program over the stacked node axis:

    train:   params_i <- local SGD on node i's shard        (vmap of the scan)
    mix:     params   <- W @ params        (mixing-matrix matmul on the MXU)

PushSum mixes with the COLUMN-stochastic version of the topology (each node
splits its mass among out-neighbors, so column sums are 1 and total mass is
conserved) and augments each node with a scalar weight w_i mixed by the same
matrix; the de-biased estimate params_i / w_i recovers the uniform average on
directed graphs where row-stochastic gossip would converge to a degree-biased
one.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.algorithms.fedavg import FedAvgAPI
from fedml_tpu.core.config import FedConfig
from fedml_tpu.data import FedDataset
from fedml_tpu.distributed.topology import SymmetricTopologyManager
from fedml_tpu.models import ModelBundle
from fedml_tpu.parallel.local import finalize_metrics


def mix_stacked(stacked, W: jax.Array):
    """new_i = sum_j W[i,j] * x_j for every leaf: einsum on the node axis."""
    return jax.tree.map(
        lambda x: jnp.einsum(
            "ij,j...->i...", W, x.astype(jnp.float32)
        ).astype(x.dtype),
        stacked,
    )


class DecentralizedFedAPI(FedAvgAPI):
    """Gossip simulator: every node holds its own model; rounds alternate
    local training and neighbor mixing. 'Aggregation' for eval purposes is
    the node average (consensus estimate)."""

    mode: str = "dsgd"  # dsgd | pushsum

    def __init__(self, dataset: FedDataset, config: FedConfig,
                 bundle: Optional[ModelBundle] = None,
                 topology: Optional[SymmetricTopologyManager] = None,
                 mode: str = "dsgd"):
        self.mode = mode
        n = dataset.num_clients
        if topology is None:
            topology = SymmetricTopologyManager(n, neighbor_num=2, seed=config.seed)
            topology.generate_topology()
        self.topology = topology
        W = np.asarray(topology.mixing_matrix, np.float32)
        if mode == "pushsum":
            # column-stochastic: node j pushes 1/out_degree(j) to each
            # out-neighbor; W @ ones is NOT ones, which is exactly what the
            # ps_weights correction tracks.
            A = (W > 0).astype(np.float32)
            W = A / A.sum(axis=0, keepdims=True)
        self.W = jnp.asarray(W)
        super().__init__(dataset, config, bundle)
        # per-node model replicas + pushsum weights
        self.node_vars = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape).copy(), self.variables
        )
        self.ps_weights = jnp.ones((n,), jnp.float32)

    def build_round_step(self):
        local_train = self._local_train
        W = self.W
        pushsum = self.mode == "pushsum"

        @jax.jit
        def round_step(node_vars, ps_weights, cx, cy, cm, counts, rng):
            C = cx.shape[0]
            keys = jax.random.split(rng, C)
            res = jax.vmap(local_train)(node_vars, cx, cy, cm, counts, keys)
            mixed = mix_stacked(res.variables, W)
            new_ps = W @ ps_weights if pushsum else ps_weights
            train_loss = jnp.sum(res.train_loss * counts) / jnp.sum(counts)
            return mixed, new_ps, train_loss

        return round_step

    def _pulse_cohort(self, round_idx: int):
        # gossip rounds train EVERY node, ignoring client sampling — the
        # base implementation would profile a phantom sampled cohort
        return np.arange(self.dataset.num_clients, dtype=np.int64)

    def _run_round_inner(self, round_idx: int) -> float:
        # the traced-span wrapper is the inherited run_round (fedavg.py);
        # overriding the INNER hook keeps gossip rounds on the one timeline
        from fedml_tpu.core.rng import round_key

        cx, cy, cm, counts = self.dataset.client_slice(np.arange(self.dataset.num_clients))
        rk = round_key(self.root_key, round_idx)
        self.node_vars, self.ps_weights, loss = self._round_step(
            self.node_vars, self.ps_weights, cx, cy, cm,
            jnp.asarray(counts, jnp.float32), rk,
        )
        self._update_consensus()
        return float(loss)

    def _update_consensus(self):
        """Refresh self.variables = node average (de-biased under pushsum) —
        the consensus estimate global eval runs on. Shared by the simulator
        and mesh forms so the eval semantics cannot drift apart."""
        debias = (self.ps_weights if self.mode == "pushsum"
                  else jnp.ones_like(self.ps_weights))
        self.variables = jax.tree.map(
            lambda x: jnp.mean(
                x.astype(jnp.float32) / debias.reshape((-1,) + (1,) * (x.ndim - 1)),
                axis=0,
            ).astype(x.dtype),
            self.node_vars,
        )

    def consensus_distance(self) -> float:
        """Mean squared distance of node models from their average — the
        convergence diagnostic of gossip algorithms."""
        avg = self.variables
        d = jax.tree.map(
            lambda x, a: jnp.sum(jnp.square(x.astype(jnp.float32) - a[None].astype(jnp.float32))),
            self.node_vars, avg,
        )
        total = float(jax.tree.reduce(jnp.add, d, jnp.zeros(())))
        return total / self.dataset.num_clients

    def evaluate_node(self, node_idx: int) -> dict:
        """Per-node eval on the global pool (reference tracks per-client
        streaming performance)."""
        node = jax.tree.map(lambda x: x[node_idx], self.node_vars)
        sums = self._eval(node, self.dataset.test_x, self.dataset.test_y, self.dataset.test_mask)
        return finalize_metrics(jax.tree.map(np.asarray, sums))


class MeshDecentralizedFedAPI(DecentralizedFedAPI):
    """Gossip with nodes sharded over a device Mesh — the distributed form
    of DSGD/PushSum (reference decentralized_worker_manager.py:41-46 runs it
    as per-neighbor MPI sends). Node state, data, and the mixing matrix
    columns live sharded in each device's HBM; the mix is a masked
    partial-sum all-reduce (see parallel/gossip.py). Math is identical to
    the einsum simulator up to psum reduction order.

    ``num_clients`` must be a multiple of the mesh's node-axis size.
    """

    def __init__(self, dataset: FedDataset, config: FedConfig,
                 bundle: Optional[ModelBundle] = None,
                 topology: Optional[SymmetricTopologyManager] = None,
                 mode: str = "dsgd", mesh=None):
        from fedml_tpu.parallel.mesh import client_mesh

        self.mesh = mesh or client_mesh(axis="nodes")
        n_axis = dict(zip(self.mesh.axis_names,
                          self.mesh.devices.shape)).get("nodes")
        if n_axis is None:
            raise ValueError(
                f"mesh must have a 'nodes' axis, got {self.mesh.axis_names}")
        if dataset.num_clients % n_axis:
            raise ValueError(
                f"num_clients ({dataset.num_clients}) must be a multiple of "
                f"the mesh 'nodes' axis ({n_axis})")
        super().__init__(dataset, config, bundle, topology, mode)
        self._placed = None  # sharded (W, node_vars, ps, data) after round 0

    def build_round_step(self):
        from fedml_tpu.parallel.gossip import make_gossip_round

        return make_gossip_round(self._local_train, self.mesh,
                                 pushsum=self.mode == "pushsum")

    def _run_round_inner(self, round_idx: int) -> float:
        from fedml_tpu.core.rng import round_key
        from fedml_tpu.parallel.gossip import place_gossip_inputs

        if self._placed is None:
            cx, cy, cm, counts = self.dataset.client_slice(
                np.arange(self.dataset.num_clients))
            W, self.node_vars, self.ps_weights, data = place_gossip_inputs(
                self.mesh, self.W, self.node_vars, self.ps_weights,
                (cx, cy, cm, jnp.asarray(counts, jnp.float32)))
            self._placed = (W, data)
        W, (cx, cy, cm, counts) = self._placed
        rk = round_key(self.root_key, round_idx)
        keys = jax.device_put(
            jax.random.split(rk, self.dataset.num_clients),
            jax.sharding.NamedSharding(self.mesh,
                                       jax.sharding.PartitionSpec("nodes")))
        self.node_vars, self.ps_weights, loss = self._traced_device_step(
            "gossip", round_idx, self._round_step,
            self.node_vars, self.ps_weights, W, cx, cy, cm, counts, keys)
        self._update_consensus()
        return float(loss)
