"""fedplan: cost-model-steered per-stage conv lowering selection.

fedpack (ops/packed_conv.py) gave the packed schedule three lowerings per
conv — ``blockdiag`` / ``grouped`` / ``off`` — but one GLOBAL flag the user
must guess, while the right answer is stage-dependent: a C=16 stage at K=4
fills only half the MXU output lanes under any useful-only lowering (the
block-diagonal GEMM's explicit K*Co lanes buy real fill there), whereas a
C=64 stage already saturates at K*Co >= 128 and the block form just streams
K x structural zeros through full lanes. fedcost (obs/cost.py) derives all
of this from pre-optimization HLO with no compile and no execution — this
module closes the ROADMAP loop and uses that table to *choose*.

:class:`LoweringPlanner` (via :func:`plan_lowering`) discovers the model's
forward conv stages by lowering the STANDARD model once, then scores each
``{blockdiag, grouped, off}`` candidate per stage by lowering a tiny
fwd+grad micro-program of just that conv at K lanes and reading fedcost's
table back. Scoring is lexicographic on

1. **effective output-lane ceiling** — the flop-weighted streamed-basis
   ceiling of the candidate's micro-program, where the ``grouped``
   candidate's lane-folding convs (``feature_group_count=K``) are credited
   with the H4 expansion fill ``min(K*N_group, 128)/128``: docs/perf.md H4
   measured the TPU backend expanding the *explicit* grouped op
   block-diagonally itself, so its realizable lanes are the expanded ones
   while its streamed FLOPs stay useful-only. The per-lane vmap (``off``)
   lowers to the statically identical grouped conv but is scored at its
   parsed per-group fill — it is the probe's control, and the asymmetry
   encodes exactly the H4 bet that ``lanes_probe --mode auto`` adjudicates
   on silicon;
2. **useful-FLOPs fraction** (a lane-equal tie goes to the lowering that
   does not stream K x structural zeros);
3. fewer operand bytes (no explicit im2col patch matrix).

The plan's headline ``predicted_ceiling`` is the USEFUL-flop-weighted
effective ceiling over the chosen stages. Useful FLOPs are invariant
across candidates, so the per-stage argmax provably dominates every
uniform (single global flag) assignment on the same metric —
``uniform_ceiling(impl)`` is computed from the same candidate records so
tests can pin the inequality. ``predicted_static_ceiling`` is the
streamed-basis parsed prediction that the post-first-call self-check in
``obs/cost.attribute_program`` compares against the realized program's
ceiling (a planner bug should be loud, not silent).

Caching: candidate micro-lowerings are cached per
``(stage_shape, K, dtype, batch, impl, jax_version)`` and whole plans per
``(model_name, stage_shapes, K, dtype, batch, jax_version)`` — repeated
runs and the prefetcher never re-lower a candidate. Hit/miss counts feed
the ``[t1] plan-cache:`` conftest session line and the ``plan`` registry
lane.
"""

from __future__ import annotations

import dataclasses
import logging
import re
import threading
from typing import Any, Optional

from fedml_tpu.obs import cost as _cost

log = logging.getLogger("fedml_tpu.plan")

__all__ = [
    "PlanStage", "LoweringPlan", "plan_lowering", "score_stage",
    "cache_stats", "reset_plan_cache", "DEFAULT_SELF_CHECK_TOL",
]

#: candidate lowerings enumerated per stage, in tie-break preference order
#: (later entries win ties on equal (ceiling, useful_frac, bytes) only by
#: being scored first — the sort is stable)
CANDIDATE_IMPLS = ("blockdiag", "grouped", "off")

#: |realized - predicted| static-ceiling divergence (absolute, on the
#: streamed basis) above which the post-first-call self-check warns. The
#: realized round program carries ops the per-stage micro-programs do not
#: (dense head, loss, optimizer dots), so the default absorbs that skew.
DEFAULT_SELF_CHECK_TOL = 0.15

_WINDOW_RE = re.compile(r"window=\{([^}]*)\}")
_WIN_SIZE_RE = re.compile(r"size=(\d+)x(\d+)")
_WIN_STRIDE_RE = re.compile(r"stride=(\d+)x(\d+)")
_WIN_PAD_RE = re.compile(r"pad=([0-9_x]+)")


# -- plan data model ---------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class PlanStage:
    """One forward conv stage of the model with its chosen lowering. All
    fields are hashable scalars/tuples: the plan travels inside flax module
    fields and jit closures, so it must hash and compare by value."""

    kh: int
    kw: int
    ci: int
    co: int
    strides: int
    h: int
    w: int
    padding: str
    count: int                 # identical call sites in the forward pass
    impl: str                  # winner: blockdiag | grouped | off
    eff_ceiling: float         # winner's effective out-lane ceiling
    ceiling: float             # winner's parsed (streamed-basis) ceiling
    useful_frac: float         # winner's useful/streamed FLOPs
    flops_frac: float          # stage useful FLOPs / model conv total
    dominated: bool            # flops_frac < cost.DOMINATED_FRAC
    #: (impl, eff_ceiling, reason-it-lost) per rejected candidate
    alternatives: tuple = ()

    @property
    def shape(self) -> tuple:
        return (self.kh, self.kw, self.ci, self.co, self.strides,
                self.h, self.w, self.padding)

    def label(self) -> str:
        short = {"blockdiag": "bd", "grouped": "grp", "off": "off"}
        tag = f"{short.get(self.impl, self.impl)}@{self.co}"
        return tag + (f"x{self.count}" if self.count > 1 else "")


@dataclasses.dataclass(frozen=True)
class LoweringPlan:
    """Per-stage impl map plus the static predictions it was chosen by.

    Accepted anywhere a ``packed_conv`` lowering string is today: the
    packed flax ``Conv`` resolves its stage through :meth:`impl_for`, the
    fallback machinery labels it "auto", and ``cost_hints['plan']`` rides
    it into ``attribute_program`` for the self-check.
    """

    model_name: str
    lanes: int
    dtype: str
    batch: int
    jax_version: str
    stages: tuple            # tuple[PlanStage, ...]
    predicted_ceiling: float          # useful-weighted effective basis
    predicted_static_ceiling: float   # streamed-weighted parsed basis
    useful_flops_frac: float
    #: ((impl, useful-weighted eff ceiling if used globally), ...)
    uniform: tuple = ()
    self_check_tol: float = DEFAULT_SELF_CHECK_TOL

    def impl_for(self, kh: int, kw: int, ci: int, co: int, strides: int,
                 h: int, w: int) -> str:
        """Resolve one conv call site to its lowering: exact stage-shape
        match first, then spatial-agnostic (a packed twin may see padded
        spatial dims), else 'grouped' — useful-only, valid for any conv."""
        for s in self.stages:
            if (s.kh, s.kw, s.ci, s.co, s.strides, s.h, s.w) == \
                    (kh, kw, ci, co, strides, h, w):
                return s.impl
        for s in self.stages:
            if (s.kh, s.kw, s.ci, s.co, s.strides) == \
                    (kh, kw, ci, co, strides):
                return s.impl
        return "grouped"

    @property
    def hint_impl(self) -> str:
        """The ``apply_packing`` impl hint for a program built from this
        plan: 'blockdiag' whenever ANY stage uses the block GEMM (its dots
        must get useful-FLOP columns), else 'grouped'."""
        return ("blockdiag"
                if any(s.impl == "blockdiag" for s in self.stages)
                else "grouped")

    def selection_ceiling(self) -> float:
        """Predicted ceiling over NON-dominated stages only — the metric
        lane-count selection compares, so a tiny 1x1 stage (<1% of the
        program's FLOPs, obs/cost.DOMINATED_FRAC) can never flip K."""
        live = [s for s in self.stages if not s.dominated] or list(self.stages)
        den = sum(s.flops_frac for s in live)
        if den <= 0:
            return self.predicted_ceiling
        return sum(s.flops_frac * s.eff_ceiling for s in live) / den

    def uniform_ceiling(self, impl: str) -> Optional[float]:
        for name, ceil in self.uniform:
            if name == impl:
                return ceil
        return None

    def summary_str(self) -> str:
        stages = " ".join(s.label() for s in self.stages)
        return f"K={self.lanes} {stages} pred={self.predicted_ceiling:.3f}"

    def to_dict(self) -> dict:
        return {
            "model": self.model_name,
            "lanes": self.lanes,
            "dtype": self.dtype,
            "batch": self.batch,
            "jax_version": self.jax_version,
            "predicted_ceiling": self.predicted_ceiling,
            "predicted_static_ceiling": self.predicted_static_ceiling,
            "useful_flops_frac": self.useful_flops_frac,
            "uniform": {k: v for k, v in self.uniform},
            "summary": self.summary_str(),
            "stages": [
                {"kh": s.kh, "kw": s.kw, "ci": s.ci, "co": s.co,
                 "strides": s.strides, "h": s.h, "w": s.w,
                 "padding": s.padding, "count": s.count, "impl": s.impl,
                 "eff_ceiling": s.eff_ceiling, "ceiling": s.ceiling,
                 "useful_frac": s.useful_frac, "flops_frac": s.flops_frac,
                 "dominated": s.dominated,
                 "alternatives": [list(a) for a in s.alternatives]}
                for s in self.stages
            ],
        }


# -- caches (the plan key contract, DESIGN.md §15) ---------------------------

_lock = threading.Lock()
#: (stage_shape, K, dtype, batch, impl, jax_version) -> candidate record
_CANDIDATES: dict = {}
#: (model_name, stage_shapes, K, dtype, batch, jax_version) -> LoweringPlan
_PLANS: dict = {}
#: (model_name, input_shape, input_dtype, batch, jax_version) -> stage list
#: — discovery lowers the WHOLE standard eval apply, the single most
#: expensive lowering in a plan build, and is K/impl-independent
_STAGES: dict = {}
#: process-lifetime hit/miss counts for the conftest ``[t1] plan-cache:``
#: session line — NEVER reset by reset_plan_cache (they describe the
#: session, not one run)
_SESSION = {"hits": 0, "misses": 0}
_REG_GROUP: dict = {"group": None}


def _plan_group():
    g = _REG_GROUP["group"]
    if g is None:
        from fedml_tpu.obs import default_registry

        g = _REG_GROUP["group"] = default_registry().group("plan")
    return g


def _count(kind: str) -> None:
    if kind in _SESSION:
        _SESSION[kind] += 1
    try:
        g = _plan_group()
        g[kind] = g.get(kind, 0) + 1
    except Exception:
        pass


def cache_stats() -> dict:
    """Process-lifetime candidate/plan cache hits and misses (a miss =
    one real ``jit(...).lower()``)."""
    with _lock:
        return dict(_SESSION)


def reset_plan_cache() -> None:
    """Drop cached candidates/plans (tests); session hit/miss counts and
    the registry group handle survive by design."""
    with _lock:
        _CANDIDATES.clear()
        _PLANS.clear()
        _STAGES.clear()
    _REG_GROUP["group"] = None


# -- stage discovery ---------------------------------------------------------

def _parse_window(line: str):
    """(kh, kw, stride, padding) from an HLO conv's window attr; spatial
    window defaults are printed only when non-trivial."""
    kh = kw = stride = 1
    padded = False
    m = _WINDOW_RE.search(line)
    if m:
        win = m.group(1)
        ms = _WIN_SIZE_RE.search(win)
        if ms:
            kh, kw = int(ms.group(1)), int(ms.group(2))
        mst = _WIN_STRIDE_RE.search(win)
        if mst:
            stride = int(mst.group(1))
        mp = _WIN_PAD_RE.search(win)
        if mp:
            padded = any(int(p) for p in re.split("[_x]", mp.group(1)))
    # 1x1 SAME == VALID; report SAME so micro-programs match either way
    padding = "SAME" if (padded or (kh == 1 and kw == 1)) else "VALID"
    return kh, kw, stride, padding


def _fwd_conv_stages(hlo_text: str) -> list[dict]:
    """The model's forward conv stages from its lowered eval HLO: one
    entry per distinct (kh, kw, ci, co, strides, h, w, padding), with the
    number of identical call sites. Grouped convs (fgc > 1) are skipped —
    standard models have none; a future depthwise family would plan its
    own stages."""
    mod = _cost.parse_hlo_module(hlo_text)
    mult, _unknown = _cost._comp_multipliers(mod)
    stages: dict[tuple, int] = {}
    for cname, comp in mod["computations"].items():
        count = mult.get(cname, 0)
        if count <= 0:
            continue
        for instr in comp.values():
            if instr["op"] != "convolution":
                continue
            m = _cost._DIM_LABELS_RE.search(instr["line"])
            if not m or instr["dims"] is None:
                continue
            lhs_spec, ker_spec, out_spec = m.groups()
            fgc = 1
            mg = _cost._ATTR_INT_RE["feature_group_count"].search(
                instr["line"])
            if mg:
                fgc = int(mg.group(1))
            if fgc != 1:
                continue
            kernel = comp.get(instr["operands"][1]) \
                if len(instr["operands"]) > 1 else None
            lhs = comp.get(instr["operands"][0]) \
                if instr["operands"] else None
            if kernel is None or kernel.get("dims") is None \
                    or lhs is None or lhs.get("dims") is None:
                continue
            kdims, ldims = kernel["dims"], lhs["dims"]
            if len(kdims) != len(ker_spec) or len(ldims) != len(lhs_spec):
                continue
            ci = next((kdims[i] for i, ch in enumerate(ker_spec)
                       if ch == "i"), 1)
            co = next((kdims[i] for i, ch in enumerate(ker_spec)
                       if ch == "o"), 1)
            spatial = [ldims[i] for i, ch in enumerate(lhs_spec)
                       if ch.isdigit()]
            if len(spatial) != 2:
                continue
            kh, kw, stride, padding = _parse_window(instr["line"])
            key = (int(kh), int(kw), int(ci), int(co), int(stride),
                   int(spatial[0]), int(spatial[1]), padding)
            stages[key] = stages.get(key, 0) + int(count)
    return [
        {"kh": k[0], "kw": k[1], "ci": k[2], "co": k[3], "strides": k[4],
         "h": k[5], "w": k[6], "padding": k[7], "count": n}
        for k, n in sorted(stages.items())
    ]


def model_conv_stages(bundle, batch: int = 4) -> list[dict]:
    """Discover a bundle's forward conv stages by lowering the STANDARD
    model's eval apply once (abstract avals only — no init, no compile).
    Cached per (model, input shape/dtype, batch, jax version): discovery
    is K/impl-independent and the whole-model lowering is the single most
    expensive step of a plan build."""
    import jax
    import jax.numpy as jnp

    key = (bundle.name, tuple(bundle.input_shape),
           str(bundle.input_dtype), batch, jax.__version__)
    with _lock:
        hit = _STAGES.get(key)
    if hit is not None:
        return [dict(s) for s in hit]
    x = jax.ShapeDtypeStruct((batch,) + tuple(bundle.input_shape),
                             bundle.input_dtype)
    variables = jax.eval_shape(
        lambda r: bundle.init(r, batch_size=batch), jax.random.PRNGKey(0))
    lowered = jax.jit(bundle.apply_eval).lower(variables, x)
    stages = _fwd_conv_stages(
        lowered.compiler_ir(dialect="hlo").as_hlo_text())
    with _lock:
        _STAGES[key] = stages
    return [dict(s) for s in stages]


# -- per-stage candidate scoring ---------------------------------------------

def _stage_bytes(ops) -> float:
    return float(sum(o["bytes"] * o["count"] for o in ops))


def _eff_out_ceiling(ops, lanes: int, credit_grouped: bool) -> float:
    """Flop-weighted effective output-lane ceiling: parsed fills, except
    that (when ``credit_grouped``) lane-folding grouped convs get the H4
    expansion fill ``min(K*N_group, 128)/128`` — the mapping the TPU
    backend was measured to pick for the explicit fgc=K op."""
    total = sum(o["flops"] * o["count"] for o in ops)
    if total <= 0:
        return 0.0
    acc = 0.0
    for o in ops:
        fill = o["out_lane_fill"]
        if (credit_grouped and o["kind"] == "conv"
                and o["groups"] == lanes and o["n"] > 1
                and o["n"] != o["k"]):
            fill = min(o["n"] * lanes, _cost.MXU_LANES) / _cost.MXU_LANES
        acc += o["flops"] * o["count"] * fill
    return acc / total


def _lower_candidate(stage: dict, impl: str, lanes: int, dtype_name: str,
                     batch: int) -> dict:
    """Lower ONE (stage, impl, K) fwd+grad micro-program and read fedcost's
    table back. jit(...).lower() only — no compile, no execution."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.ops import packed_conv as pc

    fn = {"blockdiag": pc.conv_blockdiag, "grouped": pc.conv_grouped,
          "off": pc.conv_vmap}[impl]
    dt = jnp.dtype(dtype_name)
    strides, padding = stage["strides"], stage["padding"]

    def loss(xs, ws):
        y = fn(xs, ws, strides, padding)
        return jnp.sum(jnp.square(y.astype(jnp.float32)))

    xs = jax.ShapeDtypeStruct(
        (lanes, batch, stage["h"], stage["w"], stage["ci"]), dt)
    ws = jax.ShapeDtypeStruct(
        (lanes, stage["kh"], stage["kw"], stage["ci"], stage["co"]), dt)
    lowered = jax.jit(jax.grad(loss, argnums=(0, 1))).lower(xs, ws)
    ops, unknown = _cost.op_table(
        lowered.compiler_ir(dialect="hlo").as_hlo_text())
    _cost.apply_packing(
        ops, lanes, "blockdiag" if impl == "blockdiag" else "grouped")
    streamed = sum(o["flops"] * o["count"] for o in ops)
    useful = sum(o.get("useful_flops", o["flops"]) * o["count"] for o in ops)
    return {
        "impl": impl,
        "lanes": lanes,
        "ceiling": _eff_out_ceiling(ops, lanes, credit_grouped=False),
        "eff_ceiling": _eff_out_ceiling(
            ops, lanes, credit_grouped=(impl == "grouped")),
        "streamed_flops": streamed,
        "useful_flops": useful,
        "useful_frac": (useful / streamed) if streamed else 1.0,
        "bytes": _stage_bytes(ops),
        "unknown_trip_counts": unknown,
    }


def _candidate(stage: dict, impl: str, lanes: int, dtype_name: str,
               batch: int) -> dict:
    shape = (stage["kh"], stage["kw"], stage["ci"], stage["co"],
             stage["strides"], stage["h"], stage["w"], stage["padding"])
    import jax

    key = (shape, lanes, dtype_name, batch, impl, jax.__version__)
    with _lock:
        hit = _CANDIDATES.get(key)
    if hit is not None:
        _count("hits")
        return hit
    _count("misses")
    rec = _lower_candidate(stage, impl, lanes, dtype_name, batch)
    with _lock:
        _CANDIDATES[key] = rec
    return rec


def _lost_reason(cand: dict, winner: dict) -> str:
    if cand["eff_ceiling"] + 1e-9 < winner["eff_ceiling"]:
        return (f"lower effective lane ceiling "
                f"({cand['eff_ceiling']:.3f} vs {winner['eff_ceiling']:.3f})")
    if cand["useful_frac"] + 1e-9 < winner["useful_frac"]:
        return (f"lane-equal but streams {cand['lanes']}x structural zeros "
                f"(useful {cand['useful_frac']:.2f} vs "
                f"{winner['useful_frac']:.2f})")
    if cand["impl"] == "off":
        return ("statically identical grouped-conv lowering without the "
                "explicit fgc=K mapping (H4 expansion credit goes to the "
                "explicit op; per-lane vmap is the probe's control)")
    return (f"tie broken on operand bytes "
            f"({cand['bytes']:.0f} vs {winner['bytes']:.0f})")


def score_stage(stage: dict, lanes: int, dtype_name: str = "float32",
                batch: int = 4) -> dict:
    """All candidate records for one stage at K lanes, plus the winner by
    the documented lexicographic score. Public for ``lanes_probe --mode
    auto``, which replays exactly this choice against measured time."""
    cands = [_candidate(stage, impl, lanes, dtype_name, batch)
             for impl in CANDIDATE_IMPLS]
    ranked = sorted(
        cands, key=lambda c: (c["eff_ceiling"], c["useful_frac"],
                              -c["bytes"]), reverse=True)
    winner = ranked[0]
    return {
        "stage": dict(stage),
        "winner": winner,
        "candidates": {c["impl"]: c for c in cands},
        "reasons": {c["impl"]: _lost_reason(c, winner)
                    for c in cands if c is not winner},
    }


# -- the planner -------------------------------------------------------------

def _stage_weight(s: dict) -> float:
    """Canonical useful FLOPs of a scored stage: the ``off`` candidate's
    parsed count (pure conv work, no patch machinery) times call sites.
    Using ONE impl-invariant weight for every candidate is what makes the
    per-stage argmax provably dominate every uniform assignment."""
    return s["candidates"]["off"]["useful_flops"] * s["stage"]["count"]


def _weighted_ceiling(scored: list[dict], pick) -> float:
    """Useful-flop-weighted effective ceiling over stages, each stage's
    candidate chosen by ``pick(scored_stage) -> candidate``."""
    num = den = 0.0
    for s in scored:
        w = _stage_weight(s)
        num += w * pick(s)["eff_ceiling"]
        den += w
    return num / den if den else 0.0


def plan_lowering(bundle, lanes, dtype=None, batch: int = 4,
                  self_check_tol: float = DEFAULT_SELF_CHECK_TOL
                  ) -> LoweringPlan:
    """Build the :class:`LoweringPlan` for ``bundle`` at ``lanes``
    co-scheduled clients.

    ``lanes`` may be an int (the execution path passes the concrete lane
    count the packing schedule already fixed) or a sequence of candidate
    counts (planning tools): each K is planned and the one with the best
    predicted ceiling over NON-dominated stages wins — a tiny 1x1 stage
    never flips the lane count (the ``dominated_frac`` contract,
    obs/cost.py). ``dtype`` defaults to the module's compute dtype.
    """
    import jax
    import jax.numpy as jnp

    if not isinstance(lanes, int):
        ks = sorted({int(k) for k in lanes if int(k) > 1})
        if not ks:
            raise ValueError(f"no usable lane candidates in {lanes!r}")
        plans = [plan_lowering(bundle, k, dtype=dtype, batch=batch,
                               self_check_tol=self_check_tol) for k in ks]
        return max(plans, key=lambda p: p.selection_ceiling())
    if lanes < 2:
        raise ValueError("plan_lowering needs lanes >= 2 (one lane has "
                         "nothing to co-schedule; resolve 'off' instead)")

    dt = jnp.dtype(dtype if dtype is not None
                   else getattr(bundle.module, "dtype", jnp.float32))
    dtype_name = dt.name
    stages = model_conv_stages(bundle, batch=batch)
    if not stages:
        raise ValueError(
            f"model {bundle.name!r} has no forward conv stages to plan")
    shapes = tuple(
        (s["kh"], s["kw"], s["ci"], s["co"], s["strides"], s["h"], s["w"],
         s["padding"], s["count"]) for s in stages)
    pkey = (bundle.name, shapes, lanes, dtype_name, batch, jax.__version__)
    with _lock:
        hit = _PLANS.get(pkey)
    if hit is not None:
        _count("hits")
        return hit

    scored = [score_stage(s, lanes, dtype_name, batch) for s in stages]
    model_useful = sum(_stage_weight(s) for s in scored) or 1.0

    plan_stages = []
    for s in scored:
        st, w = s["stage"], s["winner"]
        frac = _stage_weight(s) / model_useful
        plan_stages.append(PlanStage(
            kh=st["kh"], kw=st["kw"], ci=st["ci"], co=st["co"],
            strides=st["strides"], h=st["h"], w=st["w"],
            padding=st["padding"], count=st["count"], impl=w["impl"],
            eff_ceiling=round(w["eff_ceiling"], 4),
            ceiling=round(w["ceiling"], 4),
            useful_frac=round(w["useful_frac"], 4),
            flops_frac=round(frac, 4),
            dominated=frac < _cost.DOMINATED_FRAC,
            alternatives=tuple(
                (impl, round(s["candidates"][impl]["eff_ceiling"], 4),
                 reason)
                for impl, reason in sorted(s["reasons"].items())),
        ))

    predicted = _weighted_ceiling(scored, lambda s: s["winner"])
    uniform = tuple(
        (impl, round(_weighted_ceiling(
            scored, lambda s, i=impl: s["candidates"][i]), 4))
        for impl in CANDIDATE_IMPLS)
    # streamed-basis parsed prediction for the realized-program self-check
    num = den = 0.0
    for s in scored:
        w = s["winner"]
        f = w["streamed_flops"] * s["stage"]["count"]
        num += f * w["ceiling"]
        den += f
    streamed_total = den or 1.0
    useful_total = sum(_stage_weight(s) for s in scored)

    plan = LoweringPlan(
        model_name=bundle.name, lanes=lanes, dtype=dtype_name, batch=batch,
        jax_version=jax.__version__, stages=tuple(plan_stages),
        predicted_ceiling=round(predicted, 4),
        predicted_static_ceiling=round(num / streamed_total, 4),
        useful_flops_frac=round(useful_total / streamed_total, 4),
        uniform=uniform, self_check_tol=self_check_tol)
    with _lock:
        _PLANS[pkey] = plan
    _count("built")
    log.info("fedplan %s: %s", bundle.name, plan.summary_str())
    return plan
