"""fedsketch: fixed-memory, mergeable log-bucketed distribution sketches.

The pulse plane's EMA/mean lanes answer "how fast on average"; the next
ROADMAP battles key on the *distribution* — heterogeneity-aware cohort
scheduling reads the observed client-speed spread (FedML Parrot,
arXiv:2303.01778), FedBuff weighting reads the staleness tail, and a
10k-client cohort's health is its p99 train latency, not its mean. Keeping
raw samples at that scale is exactly the unbounded growth the plane's
contracts forbid, so this module is the DDSketch/HDR-histogram compromise:

- **log-bucketed**: a value ``v`` lands in bucket ``ceil(log_g(v))`` with
  ``g = (1+a)/(1-a)``; the bucket's representative ``2*g^i/(g+1)`` is
  within relative error ``a`` (default 1%) of every value it holds.
- **fixed memory**: the bucket universe is the CLOSED index range implied
  by ``[min_value, max_value]`` — values outside clamp to the edge buckets
  (and non-positive values to a dedicated zero bucket) instead of growing
  the range. No collapse pass, so the universe never shifts: ~2.1k
  possible buckets at the defaults, stored sparsely, ``nbytes`` measured.
- **exact merge**: two sketches with the same ``(alpha, min, max)`` merge
  by integer bucket-count addition — commutative, associative, and
  insert-order-independent *by construction* (no collapse means no
  order-dependent state), which is what lets per-host sketches merge into
  one cross-host distribution with zero error beyond the bucket width.
  This is the property DDSketch's collapsing variant gives up; we pin the
  universe instead so federated merges stay exact.
- **deterministic**: the bucket map is a pure function of the value (one
  ``np.log`` + ``ceil`` on float64 — same binary, same buckets), so a
  sketch-on run stays bit-identical and replays reproduce the sketch.
- **compact JSON codec**: ``encode()``/``decode()`` round-trip the sparse
  (index, count) pairs + config; the pulse stream carries it per lane so
  ``tools/trace_report.py`` can merge per-host streams after the run.

BlazeFL (arXiv:2604.03606) sets the determinism bar the whole plane holds:
everything here is integer counts over a fixed map — no clocks, no RNG.
"""

from __future__ import annotations

import math
import sys
from typing import Iterable, Optional

import numpy as np

__all__ = ["Sketch", "merge_all"]

#: universal defaults shared by every pulse lane (ms, bytes, rounds all fit
#: [1e-3, 1e15]); one universe means any two default sketches can merge
DEFAULT_ALPHA = 0.01
DEFAULT_MIN = 1e-3
DEFAULT_MAX = 1e15


class Sketch:
    """One mergeable log-bucketed histogram (module docstring)."""

    __slots__ = ("alpha", "min_value", "max_value", "_gamma", "_lg",
                 "_lo", "_hi", "zero", "n", "_bins")

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 min_value: float = DEFAULT_MIN,
                 max_value: float = DEFAULT_MAX):
        if not 0.0 < alpha < 0.5:
            raise ValueError(f"alpha must be in (0, 0.5), got {alpha}")
        if not 0.0 < min_value < max_value:
            raise ValueError(
                f"need 0 < min_value < max_value, got {min_value}, {max_value}")
        self.alpha = float(alpha)
        self.min_value = float(min_value)
        self.max_value = float(max_value)
        self._gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._lg = math.log(self._gamma)
        self._lo = int(math.ceil(math.log(self.min_value) / self._lg))
        self._hi = int(math.ceil(math.log(self.max_value) / self._lg))
        #: non-positive (and NaN/-inf) observations: exact count, value 0
        self.zero = 0
        #: total observations ever added (zero bucket included)
        self.n = 0
        self._bins: dict = {}

    # -- feed ----------------------------------------------------------------

    def add(self, values, count: Optional[int] = None) -> None:
        """Record ``values`` (scalar or array). ``count`` repeats a SCALAR
        value that many times (the cohort-amortized feed) without
        materializing the copies."""
        v = np.asarray(values, np.float64).ravel()
        if v.size == 0:
            return
        if count is not None:
            if v.size != 1:
                raise ValueError("count= only repeats a scalar value")
            reps = int(count)
            if reps <= 0:
                return
        else:
            reps = 1
        pos = (v > 0.0) & np.isfinite(v)
        n_inf = int(np.isposinf(v).sum())
        n_zero = int(v.size) - int(pos.sum()) - n_inf
        if n_zero:
            self.zero += n_zero * reps
        if n_inf:
            self._bins[self._hi] = self._bins.get(self._hi, 0) + n_inf * reps
        vp = v[pos]
        if vp.size:
            idx = np.ceil(np.log(vp) / self._lg).astype(np.int64)
            np.clip(idx, self._lo, self._hi, out=idx)
            uniq, cnt = np.unique(idx, return_counts=True)
            bins = self._bins
            for i, c in zip(uniq.tolist(), cnt.tolist()):
                bins[i] = bins.get(i, 0) + c * reps
        self.n += int(v.size) * reps

    # -- queries -------------------------------------------------------------

    def _bucket_value(self, idx: int) -> float:
        # representative of (g^(i-1), g^i]: the midpoint-in-log 2g^i/(g+1),
        # within alpha of everything the bucket holds
        return 2.0 * math.exp(idx * self._lg) / (self._gamma + 1.0)

    def quantile(self, q: float) -> Optional[float]:
        """Approximate q-quantile (relative error <= alpha inside the
        universe); None on an empty sketch."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        if self.n == 0:
            return None
        target = q * (self.n - 1)
        cum = self.zero
        if cum > target:
            return 0.0
        for idx, c in sorted(self._bins.items()):
            cum += c
            if cum > target:
                return self._bucket_value(idx)
        return self._bucket_value(self._hi)  # pragma: no cover - fp slack

    def summary(self, nd: int = 3) -> dict:
        """The compact per-round pulse summary: count + p50/p90/p99."""
        out = {"count": int(self.n)}
        if self.n:
            for name, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
                out[name] = round(float(self.quantile(q)), nd)
        return out

    @property
    def max_bins(self) -> int:
        """Structural memory bound: the bucket universe size (+ zero)."""
        return self._hi - self._lo + 2

    @property
    def nbytes(self) -> int:
        """Measured sparse-store footprint (dict + int entries)."""
        b = self._bins
        return (sys.getsizeof(b)
                + sum(sys.getsizeof(k) + sys.getsizeof(v)
                      for k, v in b.items()))

    # -- merge & codec -------------------------------------------------------

    def _compatible(self, other: "Sketch") -> bool:
        return (self.alpha == other.alpha
                and self.min_value == other.min_value
                and self.max_value == other.max_value)

    def merge(self, other: "Sketch") -> "Sketch":
        """In-place exact merge (integer bucket addition); returns self.
        Raises on mismatched universes — a silent lossy re-map would break
        the order-independence contract."""
        if not self._compatible(other):
            raise ValueError(
                f"cannot merge sketches with different universes: "
                f"(a={self.alpha}, {self.min_value}..{self.max_value}) vs "
                f"(a={other.alpha}, {other.min_value}..{other.max_value})")
        self.zero += other.zero
        self.n += other.n
        bins = self._bins
        for i, c in other._bins.items():
            bins[i] = bins.get(i, 0) + c
        return self

    def copy(self) -> "Sketch":
        out = Sketch(self.alpha, self.min_value, self.max_value)
        out.zero = self.zero
        out.n = self.n
        out._bins = dict(self._bins)
        return out

    def since(self, prev: "Sketch") -> "Sketch":
        """Exact interval delta of a cumulative sketch: the distribution of
        everything observed AFTER ``prev`` was snapshotted (bucket-wise
        subtraction — the sketch analogue of the watchdog's delta counter
        rules). ``prev`` must be an earlier snapshot of the same stream;
        counts never go negative (clamped defensively)."""
        if not self._compatible(prev):
            raise ValueError(
                "since() needs an earlier snapshot of the same universe")
        out = Sketch(self.alpha, self.min_value, self.max_value)
        out.zero = max(self.zero - prev.zero, 0)
        out.n = max(self.n - prev.n, 0)
        out._bins = {i: c - prev._bins.get(i, 0)
                     for i, c in self._bins.items()
                     if c - prev._bins.get(i, 0) > 0}
        return out

    def encode(self) -> dict:
        """Compact JSON-safe codec: config + zero count + sorted sparse
        (index, count) pairs. Sorting makes equal sketches encode to equal
        bytes — the golden-stability property the tests pin."""
        return {"v": 1, "a": self.alpha, "min": self.min_value,
                "max": self.max_value, "z": int(self.zero), "n": int(self.n),
                "b": [[int(i), int(c)] for i, c in sorted(self._bins.items())]}

    @classmethod
    def decode(cls, obj: dict) -> "Sketch":
        if not isinstance(obj, dict) or obj.get("v") != 1:
            raise ValueError(f"not a v1 sketch encoding: {obj!r}")
        out = cls(float(obj["a"]), float(obj["min"]), float(obj["max"]))
        out.zero = int(obj["z"])
        out.n = int(obj["n"])
        out._bins = {int(i): int(c) for i, c in obj.get("b", [])}
        return out

    def __eq__(self, other) -> bool:
        return (isinstance(other, Sketch) and self._compatible(other)
                and self.zero == other.zero and self.n == other.n
                and self._bins == other._bins)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Sketch(alpha={self.alpha}, n={self.n}, "
                f"buckets={len(self._bins)})")


def merge_all(sketches: Iterable[Sketch]) -> Optional[Sketch]:
    """Merge any number of compatible sketches into a fresh one (None when
    the iterable is empty) — the cross-host fold trace_report runs."""
    out = None
    for sk in sketches:
        out = sk.copy() if out is None else out.merge(sk)
    return out
