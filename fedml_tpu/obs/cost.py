"""fedcost: static per-op roofline attribution for round programs.

The flagship has sat at ~10.5% MFU across PRs 2-5 while the per-layer
explanation — CIFAR-scale convs fill at most half of the 128-wide MXU
output lanes — lived only as hand arithmetic in docs/perf.md. This module
turns that arithmetic into an instrument: every round program routed
through :func:`fedml_tpu.obs.compile.timed_build` can be lowered to HLO
and read back as a per-op table —

- conv/dot GEMM shape (M, K = kh*kw*C_in, N = C_out per feature group),
- analytic GEMM FLOPs (2*M*K*N per execution) and operand+result bytes,
- MXU output-lane fill ``min(N, 128)/128`` and reduction-lane fill
  ``min(K, 128)/128``,
- arithmetic intensity (FLOPs / bytes moved),

folded into a flop-weighted output-lane *ceiling* per program: the MFU the
program cannot exceed no matter how well XLA schedules it, because its
GEMMs leave output lanes empty. Combined with a measured duration (bench
wall clock, fedtrace compute spans) and the shared bf16 peak table this
yields achieved-FLOP/s and per-program MFU — the number the lane-packing
work on the ROADMAP is judged by.

The attribution is PURE STATIC: it only lowers (traces) the program — no
compile, no execution, no device sync — so it runs deterministically on
CPU in tier-1 and a run with attribution enabled stays bit-identical to
one without. Loop bodies are multiplied by their statically-derived trip
counts (the ``lax.scan`` counter pattern in the HLO ``while`` condition);
a loop whose trip count cannot be derived counts its body once and flags
``unknown_trip_counts`` in the summary.

This module is also the single source for FLOPs-and-peak numbers:
:data:`PEAK_BF16` / :func:`peak_flops` and :func:`fwd_flops_per_image`
moved here from bench.py so the bench, ``tools/roofline_report.py`` and
``tools/trace_report.py`` can never drift apart on ``mfu_basis``.
"""

from __future__ import annotations

import re
import threading
from typing import Optional

#: MXU systolic-array width: a GEMM contributes peak FLOPs only when both
#: the output-channel dim and the reduction dim fill this many lanes.
MXU_LANES = 128

#: stage-FLOPs fraction below which a stage is "dominated": too small to
#: matter for lowering/lane-count decisions (obs/plan.py flags rather than
#: lets a tiny 1x1 shortcut conv flip a plan). Shared with summarize()'s
#: per-stage ``dominated`` flag and ``dominated_frac`` total.
DOMINATED_FRAC = 0.01

# bf16 peak FLOP/s by TPU generation (public spec sheets), for MFU lines.
# Moved from bench.py (PR 6) so the bench headline, the roofline report and
# the trace analyzer divide by the same table.
PEAK_BF16 = (
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v6", 918e12), ("v4", 275e12),
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s4": 1, "u4": 1,
    "bf16": 2, "f16": 2, "s16": 2, "u16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "f32": 4, "s32": 4, "u32": 4,
    "f64": 8, "s64": 8, "u64": 8, "c64": 8, "c128": 16,
}


def peak_flops(device):
    """(peak_bf16_flops, matched_table_entry) for a jax device — the entry
    is reported next to every MFU so a future device kind silently
    substring-matching an old entry (e.g. a 'v6p' hitting 'v6') is visible,
    not a wrong number. (None, None) off-TPU."""
    kind = getattr(device, "device_kind", "").lower()
    for frag, peak in PEAK_BF16:
        if frag in kind:
            return peak, frag
    return None, None


def fwd_flops_per_image(bundle, variables, input_shape, batch, dtype):
    """Forward-pass FLOPs per image from XLA's own cost model (compile the
    eval forward, read cost_analysis). Falls back to the CPU backend when
    the accelerator's compiled executable doesn't expose an analysis (the
    remote-compile tunnel), and to None if both fail."""
    import jax
    import jax.numpy as jnp

    def fwd(v, x):
        return bundle.apply_eval(v, x)

    x = jnp.zeros((batch,) + tuple(input_shape), dtype)
    for backend in (None, "cpu"):
        try:
            if backend is None:
                c = jax.jit(fwd).lower(variables, x).compile()
            else:
                dev = jax.local_devices(backend=backend)[0]
                c = (jax.jit(fwd)
                     .trace(jax.device_put(variables, dev), jax.device_put(x, dev))
                     .lower(lowering_platforms=(backend,)).compile())
            ca = c.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            flops = float(ca.get("flops", 0.0))
            if flops > 0:
                return flops / batch, backend or jax.default_backend()
        except Exception:
            continue
    return None, None


# -- HLO text parsing --------------------------------------------------------
#
# The per-op table is read from the PRE-OPTIMIZATION HLO text
# (``lowered.compiler_ir("hlo").as_hlo_text()``): shapes, dim_labels and
# group counts are all printed, and the text is available from a bare
# ``jit(...).lower(...)`` without invoking the backend compiler.

_COMP_NAME_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)")
_SHAPE_RE = re.compile(r"^([a-z0-9]+)\[([0-9,]*)\]")
_DIM_LABELS_RE = re.compile(r"dim_labels=([0-9a-z?]+)_([0-9a-z?]+)->([0-9a-z?]+)")
_ATTR_INT_RE = {
    "feature_group_count": re.compile(r"feature_group_count=(\d+)"),
    "batch_group_count": re.compile(r"batch_group_count=(\d+)"),
}
_DIMS_SET_RE = {
    "lhs_contracting": re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}"),
    "rhs_contracting": re.compile(r"rhs_contracting_dims=\{([0-9,]*)\}"),
    "lhs_batch": re.compile(r"lhs_batch_dims=\{([0-9,]*)\}"),
    "rhs_batch": re.compile(r"rhs_batch_dims=\{([0-9,]*)\}"),
}
_CALLEE_RE = {
    "to_apply": re.compile(r"to_apply=%?([\w.\-]+)"),
    "calls": re.compile(r"calls=%?([\w.\-]+)"),
    "body": re.compile(r"body=%?([\w.\-]+)"),
    "condition": re.compile(r"condition=%?([\w.\-]+)"),
}
_COMMENT_RE = re.compile(r"/\*.*?\*/")
_OPCODE_RE = re.compile(r"([\w\-]+)\((.*)$")
_GTE_INDEX_RE = re.compile(r"index=(\d+)")
_CONST_INT_RE = re.compile(r"constant\((-?\d+)\)")
_COMPARE_DIR_RE = re.compile(r"direction=(\w+)")


def _parse_shape(type_text: str):
    """'bf16[64,32,32,16]{3,2,1,0}' -> ('bf16', (64,32,32,16)); tuples and
    scalars return (dtype-or-None, dims-or-None)."""
    m = _SHAPE_RE.match(type_text.strip())
    if not m:
        return None, None
    dims = tuple(int(d) for d in m.group(2).split(",") if d) \
        if m.group(2) else ()
    return m.group(1), dims


def _operand_names(arg_text: str) -> list[str]:
    """Top-level operand names from the text following 'opcode(' (balanced
    up to the matching close paren; attrs after it are ignored)."""
    depth, out, cur = 0, [], []
    for ch in arg_text:
        if ch == "(" or ch == "{" or ch == "[":
            depth += 1
            cur.append(ch)
        elif ch == ")" and depth == 0:
            break
        elif ch == ")" or ch == "}" or ch == "]":
            depth -= 1
            cur.append(ch)
        elif ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur).strip())
    return [o.lstrip("%") for o in out if o]


def _split_instr(line: str):
    """'name = TYPE opcode(rest...' -> (name, type_text, opcode, rest,
    is_root) or None. Tuple types (which contain parens and commas) are
    skipped over by balanced-paren scan, not regex."""
    s = line.strip()
    root = s.startswith("ROOT ")
    if root:
        s = s[5:]
    eq = s.find(" = ")
    if eq < 0:
        return None
    name = s[:eq].strip().lstrip("%")
    rhs = s[eq + 3:].lstrip()
    if rhs.startswith("("):
        depth = 0
        end = -1
        for i, ch in enumerate(rhs):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        if end < 0:
            return None
        type_text, rest = rhs[:end + 1], rhs[end + 1:].lstrip()
    else:
        sp = rhs.find(" ")
        if sp < 0:
            return None
        type_text, rest = rhs[:sp], rhs[sp + 1:].lstrip()
    m = _OPCODE_RE.match(rest)
    if not m:
        return None
    return name, type_text, m.group(1), m.group(2), root


def parse_hlo_module(text: str) -> dict:
    """Parse HLO text into {computation name: {instr name: instr dict}}.
    Each instr dict: dtype, dims, op, operands (names), attrs (raw line).
    ``/*index=N*/`` printer comments are stripped first — they otherwise
    corrupt both the type text and long operand lists."""
    comps: dict[str, dict] = {}
    entry = None
    cur: Optional[dict] = None
    for raw in text.splitlines():
        line = _COMMENT_RE.sub("", raw).rstrip()
        if cur is None:
            # computation header: a `{`-terminated line with no `=` (instr
            # lines always assign); name is the first token, `%`/signature
            # stripped. Matches both `region_0.9 {` and
            # `%fused (p: f32[2]) -> f32[2] {` printer styles.
            if line.endswith("{") and "=" not in line:
                m = _COMP_NAME_RE.match(line.strip())
                if m:
                    name = m.group(2).split("(")[0]
                    comps[name] = cur = {}
                    if m.group(1):
                        entry = name
            continue
        if line.strip() == "}":
            cur = None
            continue
        parts = _split_instr(line)
        if parts is None:
            continue
        name, type_text, op, rest, root = parts
        dtype, dims = _parse_shape(type_text)
        cur[name] = {
            "name": name, "dtype": dtype, "dims": dims, "op": op,
            "operands": _operand_names(rest), "line": line.strip(),
            "root": root,
        }
    return {"computations": comps, "entry": entry}


def _while_trip_count(instr: dict, comp: dict, comps: dict) -> Optional[int]:
    """Statically derive a while loop's trip count from the lax.scan
    counter pattern: condition ROOT ``compare(gte(i), constant(N)), LT``,
    init tuple element i a constant, body element i ``add(gte(i),
    constant(step))``. Returns None when the pattern doesn't hold."""
    cond_name = _CALLEE_RE["condition"].search(instr["line"])
    body_name = _CALLEE_RE["body"].search(instr["line"])
    if not cond_name or not body_name:
        return None
    cond = comps.get(cond_name.group(1))
    body = comps.get(body_name.group(1))
    if not cond or not body:
        return None
    root = next((i for i in cond.values()
                 if i["root"] and i["op"] == "compare"), None)
    if root is None:
        return None
    mdir = _COMPARE_DIR_RE.search(root["line"])
    if not mdir or mdir.group(1) not in ("LT", "LE"):
        return None
    # which side is the counter (a gte of the loop tuple), which the bound
    idx = bound = None
    for opn in root["operands"]:
        o = cond.get(opn)
        if o is None:
            continue
        if o["op"] == "get-tuple-element":
            mi = _GTE_INDEX_RE.search(o["line"])
            idx = int(mi.group(1)) if mi else None
        elif o["op"] == "constant":
            mc = _CONST_INT_RE.search(o["line"])
            bound = int(mc.group(1)) if mc else None
    if idx is None or bound is None:
        return None
    # init value: the while operand is a tuple instruction in the caller
    init = None
    tup = comp.get(instr["operands"][0]) if instr["operands"] else None
    if tup is not None and tup["op"] == "tuple" and idx < len(tup["operands"]):
        cinit = comp.get(tup["operands"][idx])
        if cinit is not None and cinit["op"] == "constant":
            mc = _CONST_INT_RE.search(cinit["line"])
            init = int(mc.group(1)) if mc else None
    if init is None:
        return None
    # step: body ROOT tuple element idx = add(gte(idx), constant(step))
    step = None
    broot = next((i for i in body.values()
                  if i["root"] and i["op"] == "tuple"), None)
    if broot is not None and idx < len(broot["operands"]):
        add = body.get(broot["operands"][idx])
        if add is not None and add["op"] == "add":
            for opn in add["operands"]:
                o = body.get(opn)
                if o is not None and o["op"] == "constant":
                    mc = _CONST_INT_RE.search(o["line"])
                    step = int(mc.group(1)) if mc else None
    if not step or step <= 0:
        return None
    trips = bound - init
    if mdir.group(1) == "LE":
        trips += 1
    trips = -(-trips // step)
    return trips if trips >= 0 else None


def _comp_multipliers(mod: dict) -> tuple[dict, bool]:
    """Execution count per computation, ENTRY = 1, loop bodies multiplied
    by their derived trip count. Returns (multipliers, any_unknown)."""
    comps, entry = mod["computations"], mod["entry"]
    mult: dict[str, int] = {}
    unknown = [False]

    def visit(cname: str, m: int):
        if m <= 0:
            return
        mult[cname] = mult.get(cname, 0) + m
        comp = comps.get(cname, {})
        for instr in comp.values():
            op, line = instr["op"], instr["line"]
            if op == "while":
                body = _CALLEE_RE["body"].search(line)
                trips = _while_trip_count(instr, comp, comps)
                if trips is None:
                    trips = 1
                    unknown[0] = True
                if body:
                    visit(body.group(1), m * trips)
            elif op in ("call", "map", "reduce", "reduce-window", "scatter",
                        "sort", "all-reduce", "select-and-scatter"):
                cal = _CALLEE_RE["to_apply"].search(line)
                if cal:
                    visit(cal.group(1), m)
            elif op == "fusion":
                cal = _CALLEE_RE["calls"].search(line)
                if cal:
                    visit(cal.group(1), m)
            elif op == "conditional":
                # branches: count each once (upper bound is one of them)
                for b in re.findall(r"branch_computations=\{([^}]*)\}", line):
                    for cn in b.split(","):
                        visit(cn.strip().lstrip("%"), m)
                for key in ("true_computation", "false_computation"):
                    mb = re.search(key + r"=%?([\w.\-]+)", line)
                    if mb:
                        visit(mb.group(1), m)

    if entry:
        visit(entry, 1)
    return mult, unknown[0]


def _prod(dims) -> int:
    out = 1
    for d in dims:
        out *= int(d)
    return out


def _lane_fill(n: int) -> float:
    return min(int(n), MXU_LANES) / MXU_LANES


def _bytes_of(instrs: list[dict]) -> float:
    total = 0.0
    for i in instrs:
        if i is None or i.get("dims") is None:
            continue
        total += _prod(i["dims"]) * _DTYPE_BYTES.get(i.get("dtype"), 4)
    return total


def _conv_op(instr: dict, comp: dict) -> Optional[dict]:
    m = _DIM_LABELS_RE.search(instr["line"])
    if not m or instr["dims"] is None:
        return None
    _lhs_spec, ker_spec, out_spec = m.groups()
    kernel = comp.get(instr["operands"][1]) if len(instr["operands"]) > 1 \
        else None
    if kernel is None or kernel.get("dims") is None:
        return None
    kdims = kernel["dims"]
    if len(kdims) != len(ker_spec):
        return None
    k_spatial = _prod(kdims[i] for i, ch in enumerate(ker_spec)
                      if ch.isdigit())
    k_in = next((kdims[i] for i, ch in enumerate(ker_spec) if ch == "i"), 1)
    fgc = 1
    mg = _ATTR_INT_RE["feature_group_count"].search(instr["line"])
    if mg:
        fgc = int(mg.group(1))
    odims = instr["dims"]
    if len(odims) != len(out_spec):
        return None
    n_total = next((odims[i] for i, ch in enumerate(out_spec) if ch == "f"), 1)
    k = k_spatial * k_in
    n = max(1, n_total // max(1, fgc))
    m_rows = _prod(odims[i] for i, ch in enumerate(out_spec) if ch != "f")
    lhs = comp.get(instr["operands"][0]) if instr["operands"] else None
    return {
        "kind": "conv", "m": int(m_rows), "k": int(k), "n": int(n),
        "groups": int(fgc), "b": 1,
        "flops": 2.0 * _prod(odims) * k,
        "bytes": _bytes_of([lhs, kernel, instr]),
    }


def _dot_op(instr: dict, comp: dict) -> Optional[dict]:
    if len(instr["operands"]) < 2 or instr["dims"] is None:
        return None
    lhs = comp.get(instr["operands"][0])
    rhs = comp.get(instr["operands"][1])
    if lhs is None or rhs is None or lhs.get("dims") is None \
            or rhs.get("dims") is None:
        return None

    def dims_set(key):
        mm = _DIMS_SET_RE[key].search(instr["line"])
        if not mm or not mm.group(1):
            return ()
        return tuple(int(d) for d in mm.group(1).split(","))

    lc, rc = dims_set("lhs_contracting"), dims_set("rhs_contracting")
    lb, rb = dims_set("lhs_batch"), dims_set("rhs_batch")
    ldims, rdims = lhs["dims"], rhs["dims"]
    k = _prod(ldims[i] for i in lc) if lc else 1
    b = _prod(ldims[i] for i in lb) if lb else 1
    m_rows = _prod(d for i, d in enumerate(ldims) if i not in lc + lb)
    n = _prod(d for i, d in enumerate(rdims) if i not in rc + rb)
    return {
        "kind": "dot", "m": int(m_rows), "k": int(k), "n": int(n),
        "groups": 1, "b": int(b),
        "flops": 2.0 * b * m_rows * k * n,
        "bytes": _bytes_of([lhs, rhs, instr]),
    }


def op_table(hlo_text: str) -> tuple[list[dict], bool]:
    """The per-op GEMM table of an HLO module: one row per conv/dot
    instruction, with its static execution count (loop-body multiplier).
    Returns (ops, unknown_trip_counts)."""
    mod = parse_hlo_module(hlo_text)
    mult, unknown = _comp_multipliers(mod)
    ops: list[dict] = []
    for cname, comp in mod["computations"].items():
        count = mult.get(cname, 0)
        if count <= 0:
            continue
        for instr in comp.values():
            row = None
            if instr["op"] == "convolution":
                row = _conv_op(instr, comp)
            elif instr["op"] == "dot":
                row = _dot_op(instr, comp)
            if row is None:
                continue
            row.update({
                "name": instr["name"], "dtype": instr["dtype"],
                "count": int(count),
                "out_lane_fill": _lane_fill(row["n"]),
                "red_lane_fill": _lane_fill(row["k"]),
            })
            # fedpack columns: packing_factor = co-scheduled clients folded
            # into this op; useful_flops = FLOPs doing real per-client
            # work. Defaults (1, = flops) — whether an op folds clients is
            # program-level knowledge, filled in by apply_packing() from
            # the builder's out-of-band hint (jax 0.4.37 drops name-stack
            # metadata from HLO text, so ops carry no marker to parse).
            row["packing_factor"] = 1
            row["useful_flops"] = row["flops"]
            row["intensity"] = (row["flops"] / row["bytes"]
                                if row["bytes"] else 0.0)
            ops.append(row)
    return ops, unknown


def apply_packing(ops: list[dict], factor: int,
                  impl: str = "blockdiag") -> list[dict]:
    """Fill a client-packed program's packing columns (in place), given the
    builder's hint that ``factor`` clients are folded per op.

    - Grouped convs with ``groups == factor`` are the K-client folding
      (the per-lane vmap's H4 lowering, or ops/packed_conv.conv_grouped);
      their analytic FLOPs are already useful-only, so only the factor is
      recorded. Patch-extraction/depthwise shapes (per-group N of 1, or
      N == K — identity-kernel im2col machinery) are excluded.
    - With ``impl == 'blockdiag'``, unbatched dots whose output AND
      reduction dims are both multiples of ``factor`` are the block GEMMs
      (ops/packed_conv.conv_blockdiag) — fwd (N = K*Co), dgrad (N = K*R)
      and wgrad (N = K*Co) all qualify — streaming ``factor`` x the useful
      FLOPs as structural zeros: ``useful_flops`` divides accordingly.

    Hint-scoped by design: it only runs on programs whose builder attached
    ``cost_hints``, never on arbitrary HLO.
    """
    if not factor or factor <= 1:
        return ops
    for o in ops:
        if (o["kind"] == "conv" and o["groups"] == factor
                and o["n"] > 1 and o["n"] != o["k"]):
            o["packing_factor"] = int(factor)
        elif (impl == "blockdiag" and o["kind"] == "dot"
                and o.get("b", 1) == 1
                and o["n"] % factor == 0 and o["k"] % factor == 0):
            o["packing_factor"] = int(factor)
            o["useful_flops"] = o["flops"] / factor
    return ops


def summarize(ops: list[dict], unknown_trip_counts: bool = False,
              top_k: int = 8) -> dict:
    """Fold a per-op table into the numbers a report prints: total GEMM
    FLOPs per program invocation, the flop-weighted MXU lane ceilings, a
    per-output-channel stage table (the docs/perf.md roofline rows), and
    the top-k ops by executed FLOPs."""
    total = sum(o["flops"] * o["count"] for o in ops)
    if total <= 0:
        return {"gemm_ops": 0, "gemm_flops_per_invocation": 0.0,
                "useful_flops_per_invocation": 0.0,
                "out_lane_ceiling": None, "red_lane_ceiling": None,
                "packing": None,
                "by_output_channels": {}, "dominated_frac": 0.0,
                "top_ops": [],
                "unknown_trip_counts": unknown_trip_counts}
    out_ceiling = sum(o["flops"] * o["count"] * o["out_lane_fill"]
                      for o in ops) / total
    red_ceiling = sum(o["flops"] * o["count"] * o["red_lane_fill"]
                      for o in ops) / total
    by_n: dict[int, float] = {}
    for o in ops:
        by_n[o["n"]] = by_n.get(o["n"], 0.0) + o["flops"] * o["count"]
    # a stage whose FLOPs are < DOMINATED_FRAC of the program is flagged
    # dominated: the planner/report must not let it steer a decision
    stage = {
        str(n): {"out_lane_fill": _lane_fill(n),
                 "flops_frac": round(f / total, 4),
                 "dominated": f / total < DOMINATED_FRAC}
        for n, f in sorted(by_n.items())
    }
    dominated_frac = sum(f for f in by_n.values()
                         if f / total < DOMINATED_FRAC) / total
    top = sorted(ops, key=lambda o: -o["flops"] * o["count"])[:top_k]
    # fedpack accounting: streamed vs useful FLOPs. `.get` defaults keep
    # hand-built op rows (tests, older callers) working unchanged.
    useful = sum(o.get("useful_flops", o["flops"]) * o["count"] for o in ops)
    max_factor = max((o.get("packing_factor", 1) for o in ops), default=1)
    packing = None
    if max_factor > 1:
        packing = {"max_factor": int(max_factor),
                   "useful_flops_frac": round(useful / total, 4)}
    return {
        "gemm_ops": len(ops),
        "gemm_flops_per_invocation": total,
        "useful_flops_per_invocation": useful,
        "out_lane_ceiling": round(out_ceiling, 4),
        "red_lane_ceiling": round(red_ceiling, 4),
        "packing": packing,
        "by_output_channels": stage,
        "dominated_frac": round(dominated_frac, 4),
        "top_ops": [
            {k: (round(v, 4) if isinstance(v, float) else v)
             for k, v in o.items() if k != "intensity"}
            | {"intensity": round(o["intensity"], 2)}
            for o in top
        ],
        "unknown_trip_counts": unknown_trip_counts,
    }


def analyze_lowered(lowered, top_k: int = 8) -> dict:
    """Full static analysis of a ``jax.stages.Lowered``: the per-op table,
    its summary, and XLA's own cost-model totals (flops/bytes with loop
    bodies counted ONCE — XLA's pre-compile convention, recorded for
    comparability with ``fwd_flops_per_image``)."""
    text = lowered.compiler_ir(dialect="hlo").as_hlo_text()
    ops, unknown = op_table(text)
    rep = {"ops": ops, "summary": summarize(ops, unknown, top_k=top_k)}
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        rep["xla_cost"] = {"flops": float(ca.get("flops", 0.0)),
                           "bytes_accessed": float(ca.get("bytes accessed", 0.0))}
    except Exception:
        rep["xla_cost"] = None
    return rep


def analyze_jitted(fn, args, top_k: int = 8) -> Optional[dict]:
    """Lower a jitted callable with its call args and analyze; None when
    the callable can't be lowered (not a jit wrapper, tracing error)."""
    lower = getattr(fn, "lower", None)
    if lower is None:
        return None
    try:
        return analyze_lowered(lower(*args), top_k=top_k)
    except Exception:
        return None


def roofline(summary: dict, measured_s: float, invocations: float = 1.0,
             peak: Optional[float] = None) -> dict:
    """Achieved-FLOP/s (and MFU when a peak is known) for a program whose
    static summary and measured execution time are both in hand. The FLOP
    basis is the analytic GEMM count (multiply-accumulates only) — the
    strict roofline convention, lower than XLA's all-HLO-flops count."""
    flops = summary.get("gemm_flops_per_invocation", 0.0) * invocations
    achieved = flops / measured_s if measured_s > 0 else 0.0
    out = {
        "gemm_flops": flops,
        "achieved_gflops_per_sec": round(achieved / 1e9, 2),
        "mfu_mac": round(achieved / peak, 4) if peak else None,
        "out_lane_ceiling": summary.get("out_lane_ceiling"),
    }
    ceiling = summary.get("out_lane_ceiling")
    if peak and ceiling:
        out["mfu_vs_ceiling"] = round((achieved / peak) / ceiling, 4)
    # fedpack honesty: when the program streams structural zeros (block-
    # diagonal packing), also report the USEFUL-work rates — the number
    # comparable across lowerings (streamed MFU flatters a packed program
    # by exactly its packing factor)
    useful = summary.get("useful_flops_per_invocation")
    if useful is not None and useful < flops / max(invocations, 1e-12):
        u = useful * invocations
        ach_u = u / measured_s if measured_s > 0 else 0.0
        out["useful_gflops_per_sec"] = round(ach_u / 1e9, 2)
        if peak:
            out["mfu_mac_useful"] = round(ach_u / peak, 4)
    return out


# -- runtime attribution (the timed_build hook) ------------------------------

#: mesh-path tag for programs whose rounds carry fedscope ``mesh_step`` /
#: ``mesh_round`` device spans — lets trace_report match a program's static
#: cost to its measured device time; sim-paradigm programs have no device
#: span and are matched against the round span instead. ``superstep_fn``
#: deliberately gets its own tag that matches NO device rows: one
#: invocation covers h rounds, so pairing it with single-round mesh_step
#: spans would overstate achieved-FLOP/s by ~h — its table stays
#: static-only (the superstep wall is reported separately by trace_report).
PROGRAM_PATHS = {
    "mesh_packed_round": "packed_mesh",
    "superstep_fn": "superstep",
}

_lock = threading.Lock()
_ENABLED = False
_TABLES: dict[str, dict] = {}   # program name -> latest attribution record


def enable_cost_attribution(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def cost_attribution_enabled() -> bool:
    return _ENABLED


_NO_ATTR = object()


def _plan_self_check(name: str, plan, summary: dict) -> Optional[dict]:
    """Post-first-call fedplan self-check: compare the realized program's
    streamed-basis lane ceiling against the plan's parsed-basis prediction
    and WARN (log + 'plan' registry counter) on divergence above the
    plan's tolerance — a planner bug should be loud, not silent. The
    realized program carries ops the per-stage micro-programs don't (dense
    head, loss, optimizer), so the tolerance is deliberately loose."""
    predicted = getattr(plan, "predicted_static_ceiling", None)
    realized = summary.get("out_lane_ceiling")
    if predicted is None or realized is None:
        return None
    tol = float(getattr(plan, "self_check_tol", 0.15))
    delta = float(realized) - float(predicted)
    ok = abs(delta) <= tol
    if not ok:
        import logging

        logging.getLogger("fedml_tpu.cost").warning(
            "fedplan self-check: program %r realized static lane ceiling "
            "%.3f diverges from the plan's prediction %.3f by %+.3f "
            "(tolerance %.3f) — the planner scored stages the program "
            "does not run, or the lowering changed under it",
            name, realized, predicted, delta, tol)
        try:
            # the plan module owns the long-lived 'plan' registry group
            # (registry groups are weakref'd — a fresh group here would
            # die, and its counter with it, before any snapshot)
            from fedml_tpu.obs.plan import _plan_group

            g = _plan_group()
            g["self_check_warn"] = g.get("self_check_warn", 0) + 1
        except Exception:
            pass
    return {"predicted_static_ceiling": float(predicted),
            "realized_static_ceiling": float(realized),
            "delta": round(delta, 4), "tolerance": tol, "ok": ok}


def configure_from(config) -> bool:
    """Read ``config.cost_attribution``; a config without the attribute
    leaves the current setting untouched (mirrors tracer.configure_from)."""
    val = getattr(config, "cost_attribution", _NO_ATTR)
    if val is not _NO_ATTR:
        enable_cost_attribution(bool(val))
    return _ENABLED


def cost_tables() -> dict:
    """Latest attribution record per program name (copy)."""
    with _lock:
        return dict(_TABLES)


def table_for(name_prefix: str) -> Optional[dict]:
    """The attribution record for one PROGRAM by name prefix — the
    class-qualified program names ("packed_step.FedOptAPI",
    "gather_step.FedProxAPI", ...) make a process running several API
    types hold one record per program, and consumers (bench.py's adaptive
    packed arm, reports) should select the program they measured instead
    of max-by-FLOPs guessing. Longest matching name wins on ties."""
    with _lock:
        hits = [k for k in _TABLES if k.startswith(name_prefix)]
        if not hits:
            return None
        return _TABLES[max(hits, key=len)]


def reset_cost_tables() -> None:
    with _lock:
        _TABLES.clear()


def attribute_program(name: str, shape_key, fn, args) -> Optional[dict]:
    """Statically attribute one built round program: lower, tabulate,
    store under ``name``, and (when tracing) emit a ``program_cost``
    instant whose args carry the trimmed summary. Never raises — a failed
    attribution returns None and the run proceeds untouched."""
    try:
        rep = analyze_jitted(fn, args)
        if rep is None:
            return None
        # fedpack hint (ops/packed_conv.py): programs whose builder marked
        # them as client-packed get their block-diag dots' packing_factor /
        # useful-FLOP columns filled in and the summary recomputed. A
        # plan-steered ("auto") program carries its LoweringPlan in the
        # hints; its blockdiag stages' dots need the useful-FLOP division
        # whenever ANY stage uses the block GEMM (plan.hint_impl).
        hints = getattr(fn, "cost_hints", None)
        plan = (hints or {}).get("plan")
        if hints and hints.get("packing_factor", 1) > 1:
            impl = hints.get("packed_conv", "blockdiag")
            if plan is not None:
                impl = getattr(plan, "hint_impl", impl)
            apply_packing(rep["ops"], int(hints["packing_factor"]), impl)
            rep["summary"] = summarize(
                rep["ops"], rep["summary"]["unknown_trip_counts"])
        record = {
            "program": name,
            "shape_key": repr(shape_key),
            "path": PROGRAM_PATHS.get(name),
            "packed_conv": (hints or {}).get("packed_conv"),
            "summary": rep["summary"],
            "xla_cost": rep["xla_cost"],
            "ops": rep["ops"],
        }
        if plan is not None:
            record["plan"] = plan.to_dict() if hasattr(plan, "to_dict") \
                else plan
            record["plan_self_check"] = _plan_self_check(
                name, plan, rep["summary"])
        with _lock:
            _TABLES[name] = record
        from fedml_tpu.obs.tracer import tracer_if_enabled

        tr = tracer_if_enabled(0)
        if tr is not None:
            import jax

            peak, entry = peak_flops(jax.devices()[0])
            tr.instant("program_cost", cat="cost", args={
                "program": name,
                "shape_key": repr(shape_key),
                "path": record["path"],
                "summary": rep["summary"],
                "xla_cost": rep["xla_cost"],
                "peak_bf16_flops": peak,
                "peak_table_entry": entry,
            })
            if plan is not None:
                tr.instant("program_plan", cat="cost", args={
                    "program": name,
                    "plan": record.get("plan"),
                    "self_check": record.get("plan_self_check"),
                })
        return record
    except Exception:
        return None
