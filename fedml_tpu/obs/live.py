"""fedpulse live exporter: streaming round-boundary telemetry.

PRs 4-6 made the observability stack deep but strictly post-hoc: spans,
registries and roofline tables land on disk and are analyzed after the run.
This module is the LIVE half — one process-wide :class:`PulsePlane` that,
at every round boundary, folds the signals the run already produces into
one JSON snapshot appended to ``pulse.jsonl``:

- the unified registry's ``time``/``wire``/``chaos``/``compile`` counter
  lanes (one ``snapshot()`` per namespace — reads, no new instrumentation),
- the latest host-pipeline stage row (``round_stats`` keys),
- the :class:`~fedml_tpu.obs.profile.ClientProfiler` aggregates (clients
  seen, participation fairness, EMA train-ms spread, top-k stragglers,
  staleness, measured store bytes),
- the profiler's fedsketch distribution lanes (train-ms, broadcast→upload
  latency, payload bytes, rounds-behind staleness) as per-round
  p50/p90/p99 + count summaries PLUS the mergeable codec, so per-host
  streams fold into one cross-host distribution after the run,
- fedcost attribution of the FLOP-dominant program against the measured
  round wall (achieved GFLOP/s, MAC-basis MFU and its share of the lane
  ceiling) when ``--cost_attribution`` is on,
- the :class:`~fedml_tpu.obs.health.HealthWatchdog` verdict for the round.

``tools/fedtop.py`` tails the file live; the Prometheus textfile mirror
(``--pulse_prometheus_dir``) re-renders each snapshot as gauges for a
node-exporter-style scraper.

Contracts (the tracer's discipline, restated for the pulse plane):

- **off by default, allocation-free when off**: ``pulse_if_enabled()`` is
  one module-global read returning ``None``; disabled call sites do no
  other work (pinned by tests/test_pulse.py's tracemalloc test);
- **bit-identity**: the plane only READS — counters, clocks, the round
  plan (a pure function of (seed, round)) — so a pulse-on run computes
  exactly the pulse-off weights;
- **atomic appends**: each snapshot is ONE ``os.write`` of one
  newline-terminated JSON line to an ``O_APPEND`` fd, so a concurrent
  tailer never observes a torn line.

Configured per run via ``--pulse_path``/``--health_*``
(:func:`configure_from`, chained from ``tracer.configure_from`` so every
existing entry point picks it up), or directly via :func:`configure` (the
bench enables a profiler-only plane with no stream).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Optional

import numpy as np

from fedml_tpu.obs.health import FederationHealthError, HealthWatchdog
from fedml_tpu.obs.profile import ClientProfiler
from fedml_tpu.obs.registry import default_registry
from fedml_tpu.obs.tracer import tracer_if_enabled

from fedml_tpu.obs.flight import recorder_if_enabled as _flight_recorder

__all__ = [
    "FederationHealthError", "LiveExporter", "PulsePlane", "configure",
    "configure_from", "plane_scope", "pulse_enabled", "pulse_if_enabled",
    "reset", "session_stats",
]

#: registry namespaces exported as pulse "lanes" every snapshot ("packed"
#: carries the fedpack fallback counters, parallel/packed.py; "plan" the
#: fedplan cache/self-check counters, obs/plan.py)
_LANES = ("time", "wire", "chaos", "compile", "packed", "plan")

#: process-lifetime stats for the conftest session summary (NEVER reset by
#: configure()/reset() — they describe the session, not one run).
#: ``overhead_pct`` is written by the tier-1 overhead-budget pin via
#: :func:`record_overhead` so the session log carries the measured number.
_SESSION = {"snapshots": 0, "runs": 0, "critical": 0, "last_path": None,
            "overhead_pct": None, "overhead_budget_pct": None}


def record_overhead(pct: float, budget_pct: float) -> None:
    """Record the measured full-plane-on vs plane-off wall delta (percent)
    from the pinned overhead-budget test; conftest prints it as the
    ``[t1] obs-overhead:`` session line for tools/t1_report.py."""
    _SESSION["overhead_pct"] = round(float(pct), 2)
    _SESSION["overhead_budget_pct"] = round(float(budget_pct), 2)


def _round_num(v, nd: int = 3):
    return round(v, nd) if isinstance(v, float) else v


def _prom_name(key: str) -> str:
    return "".join(c if c.isalnum() else "_" for c in key)


class LiveExporter:
    """Append-only ``pulse.jsonl`` writer + optional Prometheus mirror."""

    def __init__(self, path: str, prometheus_dir: Optional[str] = None):
        self.path = os.path.abspath(path)
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # O_APPEND + a single write() per snapshot = atomic line appends
        self._fd = os.open(self.path,
                           os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
        self.prometheus_dir = prometheus_dir
        if prometheus_dir:
            os.makedirs(prometheus_dir, exist_ok=True)
        self.snapshots = 0

    def emit(self, snap: dict) -> None:
        line = json.dumps(snap, separators=(",", ":"), default=float) + "\n"
        os.write(self._fd, line.encode())
        self.snapshots += 1
        _SESSION["snapshots"] += 1
        _SESSION["last_path"] = self.path
        if self.prometheus_dir:
            self._write_prom(snap)

    def _write_prom(self, snap: dict) -> None:
        """Textfile-collector mirror: flat gauges, atomically replaced so a
        scraper never reads a half-written file."""
        lines = ["# fedpulse textfile mirror (one scrape = latest round)"]

        def gauge(name: str, v) -> None:
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return
            if isinstance(v, float) and not np.isfinite(v):
                return
            lines.append(f"fedpulse_{_prom_name(name)} {v:g}")

        gauge("round", snap.get("round"))
        gauge("ts_ms", snap.get("ts_ms"))
        gauge("loss", snap.get("loss"))
        gauge("round_ms", snap.get("round_ms"))
        gauge("cohort", snap.get("cohort"))
        for k, v in (snap.get("rates") or {}).items():
            gauge(k, v)
        for lane, counters in (snap.get("lanes") or {}).items():
            for k, v in counters.items():
                gauge(f"{lane}_{k}", v)
        prof = snap.get("profile") or {}
        gauge("clients_seen", prof.get("clients_seen"))
        gauge("profile_store_bytes", prof.get("store_bytes"))
        gauge("profile_dropped_ids", prof.get("dropped_ids"))
        gauge("participation_gini", (prof.get("participation") or {}).get("gini"))
        gauge("ema_train_ms_p95", (prof.get("ema_train_ms") or {}).get("p95"))
        for lane, s in (snap.get("sketches") or {}).items():
            gauge(f"sketch_{lane}_p50", s.get("p50"))
            gauge(f"sketch_{lane}_p99", s.get("p99"))
            gauge(f"sketch_{lane}_count", s.get("count"))
        cost = snap.get("cost") or {}
        gauge("mfu_mac", cost.get("mfu_mac"))
        gauge("mfu_vs_lane_ceiling", cost.get("mfu_vs_ceiling"))
        health = snap.get("health") or {}
        sev = {"ok": 0, "warn": 1, "critical": 2}.get(health.get("state"), 0)
        lines.append(f"fedpulse_health_severity {sev}")
        tmp = os.path.join(self.prometheus_dir, ".fedpulse.prom.tmp")
        with open(tmp, "w") as f:
            f.write("\n".join(lines) + "\n")
        os.replace(tmp, os.path.join(self.prometheus_dir, "fedpulse.prom"))

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


class PulsePlane:
    """Profiler + watchdog + exporter behind one round-boundary hook."""

    def __init__(self, exporter: Optional[LiveExporter] = None,
                 profiler: Optional[ClientProfiler] = None,
                 watchdog: Optional[HealthWatchdog] = None,
                 registry=None):
        self.exporter = exporter
        self.profiler = profiler
        self.watchdog = watchdog
        #: registry whose counter lanes each snapshot reads. None (the
        #: default) resolves per call — the calling thread's registry_scope
        #: or the process default. A gateway tenant's plane is PINNED to
        #: that tenant's registry so its snapshots can never pick up another
        #: tenant's counters, whichever thread emits the round.
        self.registry = registry
        #: fedflight scope tag: the gateway pins each lane's plane to its
        #: tenant id so the flight recorder keys that lane's round window
        #: (and any quarantine bundle) to the tenant, never interleaving
        #: another tenant's rounds. None = the default federation scope.
        self.tenant: Optional[str] = None
        self._t_last_ms: Optional[float] = None
        self._round_clients = 0
        self._peak = None
        self._peak_resolved = False
        #: previous round-boundary sketch copies, for the per-round deltas
        self._prev_sketches: dict = {}
        #: fedlens rows accumulated since the last round boundary (sim
        #: stash conversions + edge per-upload stats), folded into the
        #: snapshot's ``learning`` block then cleared
        self._lens_rows: list = []

    # -- feeds ---------------------------------------------------------------

    def observe_upload(self, client_ids, round_idx: int, *,
                       train_ms: Optional[float] = None,
                       upload_bytes: Optional[float] = None,
                       staleness: float = 0.0) -> None:
        """Edge-server per-upload feed (broadcast→aggregate path): attribute
        the worker's observed round latency + payload bytes to its assigned
        logical clients. ``staleness`` is the contribution's version lag on
        the staleness sketch lane — 0 for a sync round's on-time upload
        (the default), ``server_version - trained_version`` for a fedbuff
        fold (the lane the watchdog's version_lag rule reads)."""
        ids = np.atleast_1d(np.asarray(client_ids, np.int64))
        if ids.size == 0:
            return
        self._round_clients += int(ids.size)
        if self.profiler is not None:
            per_client = (None if upload_bytes is None
                          else float(upload_bytes) / ids.size)
            self.profiler.observe(ids, round_idx, train_ms=train_ms,
                                  upload_bytes=per_client)
            # sketch lanes record the UPLOAD-granular values (one sample per
            # contribution, not per assigned logical client)
            self.profiler.observe_wire(upload_ms=train_ms,
                                       payload_bytes=upload_bytes,
                                       staleness=float(staleness))

    def observe_lens(self, client_ids, round_idx: int, *, update_norm,
                     align=None, loss_delta=None) -> None:
        """fedlens per-client learning-signal feed: per-id update norms
        plus (when the path computes them) cosine alignment vs the round
        aggregate and first-to-last-epoch loss deltas. The sim paradigms
        route their device stash here one boundary later under
        ``--async_rounds``; the edge servers feed per-upload stats. Rows
        accumulate until the next :meth:`on_round` folds them into the
        snapshot's ``learning`` block (obs/lens.fold_rows)."""
        ids = np.atleast_1d(np.asarray(client_ids, np.int64))
        if ids.size == 0:
            return
        if self.profiler is not None:
            drift = None if align is None else 1.0 - np.asarray(
                align, np.float64)
            self.profiler.observe_lens(ids, round_idx,
                                       update_norm=update_norm, drift=drift)
        self._lens_rows.append({"ids": ids, "update_norm": update_norm,
                                "align": align, "loss_delta": loss_delta})

    def observe_stale(self, rounds_behind: int) -> None:
        """Stale-contribution feed (the deadline-closed late-upload path):
        record how many rounds behind the dropped upload was on the
        ``staleness`` sketch lane — the tail FedBuff's staleness weighting
        will read; a sync run's lane is all zeros plus these."""
        if self.profiler is not None:
            self.profiler.observe_wire(staleness=max(int(rounds_behind), 0))

    def on_sim_round(self, api, round_idx: int, loss, round_ms: float):
        """Simulation-paradigm feed from the traced ``run_round`` wrapper:
        ask the API which clients the round actually trained
        (``_pulse_cohort`` — the stashed round plan by default, the full
        node set for gossip paradigms) and amortize the round wall per
        client — clients train fused under one vmap there, so no finer
        per-client wall exists."""
        ids = train_ms = None
        try:
            ids = api._pulse_cohort(round_idx)
            if ids is not None and ids.size:
                # amortize the round wall by each client's share of the
                # round's RECORDS when the API can attribute it
                # (_pulse_cohort_shares): a 3x-records client consumed ~3x
                # the materialize + compute, and this is the per-client
                # cost signal the fedsched `speed` policy ranks on. Even
                # split when shares are unavailable.
                shares = getattr(api, "_pulse_cohort_shares",
                                 lambda _ids: None)(ids)
                if shares is None:
                    train_ms = round_ms / float(ids.size)
                else:
                    train_ms = np.asarray(shares, np.float64) * round_ms
        except Exception:
            # a paradigm whose dataset/plan doesn't fit the cohort contract
            # (vertical splits etc.): keep the round snapshot, skip per-client
            ids = None
        try:
            # fedlens stash drain: the lens-armed APIs hand over the
            # round's per-client device stats ONE boundary late under
            # async_rounds (no host sync on the round path); the stash
            # carries its own round index + ids so the lag can never
            # misattribute
            pl = getattr(api, "_pulse_lens", None)
            st = pl(round_idx) if pl is not None else None
            if st is not None:
                lens_round, lens_ids, lens_stats = st
                self.observe_lens(lens_ids, lens_round, **lens_stats)
        except Exception:
            pass
        host_loss = (float(loss)
                     if isinstance(loss, (int, float))
                     and not isinstance(loss, bool) else None)
        return self.on_round(round_idx, source=type(api).__name__,
                             loss=host_loss, round_ms=round_ms,
                             cohort_ids=ids, train_ms_per_client=train_ms)

    # -- the round boundary --------------------------------------------------

    def on_round(self, round_idx: int, *, source: str,
                 loss: Optional[float] = None,
                 round_ms: Optional[float] = None, cohort_ids=None,
                 train_ms_per_client: Optional[float] = None,
                 upload_bytes: Optional[float] = None,
                 extra: Optional[dict] = None) -> dict:
        """Assemble + persist one round snapshot; returns it. Raises
        :class:`FederationHealthError` AFTER the snapshot is written when
        the watchdog escalates."""
        now_ms = time.time() * 1e3
        n_cohort = None
        if cohort_ids is not None:
            ids = np.atleast_1d(np.asarray(cohort_ids, np.int64))
            n_cohort = int(ids.size)
            if self.profiler is not None and ids.size:
                self.profiler.observe(
                    ids, round_idx, train_ms=train_ms_per_client,
                    upload_bytes=(None if upload_bytes is None
                                  else float(upload_bytes) / ids.size))
        if n_cohort is None and self._round_clients:
            n_cohort = self._round_clients
        self._round_clients = 0

        reg = self.registry if self.registry is not None else default_registry()
        lanes = {}
        for ns in _LANES:
            snap = reg.snapshot(ns)
            if snap:
                lanes[ns] = {k: _round_num(v) for k, v in snap.items()}
        wire_view = dict(lanes.get("wire", {}))
        if extra:
            wire_view.update(extra)
            lanes.setdefault("wire", {}).update(
                {k: _round_num(v) for k, v in extra.items()})

        stage_rows = reg.rows("stage")
        stage = None
        if stage_rows and stage_rows[-1].get("round") == round_idx:
            stage = {k: _round_num(v) for k, v in stage_rows[-1].items()}

        profile = (self.profiler.aggregates(round_idx,
                                            include_sketches=False)
                   if self.profiler is not None else None)
        # fedsketch block, from ONE locked copy pass: per-lane cumulative
        # percentile summary, the per-ROUND delta summary (cumulative minus
        # the previous boundary — exact bucket subtraction, the sketch form
        # of the watchdog's delta counter rules), and — only when a stream
        # will actually persist it — the mergeable codec. Sketches are
        # cumulative, so any snapshot alone carries the run-so-far
        # distribution and the LAST one is the whole run — trace_report
        # merges the last snapshot of each per-host stream.
        sketches = None
        if self.profiler is not None:
            copies = self.profiler.sketch_copies()
            if copies:
                sketches = {}
                for lane, cur in copies.items():
                    prev = self._prev_sketches.get(lane)
                    delta = cur if prev is None else cur.since(prev)
                    entry = {**cur.summary(), "round": delta.summary()}
                    if self.exporter is not None:
                        entry["enc"] = cur.encode()
                    sketches[lane] = entry
                self._prev_sketches = copies
            if profile is not None and sketches:
                # the watchdog's skew basis is THIS round's distribution:
                # the cumulative lane conflates time (a compile-heavy round
                # 0 would own the p99 for the next ~100 rounds and false-
                # fire skew on healthy runs). The snapshot's profile block
                # carries the per-round summaries; the cumulative ones live
                # at the snapshot top level, never duplicated.
                profile["sketches"] = {
                    lane: s["round"] for lane, s in sketches.items()}

        # fedlens learning block: fold the rows fed since the last
        # boundary (rank + dedupe, obs/lens.fold_rows). ABSENT — not null —
        # when no lens row arrived, so lens-off snapshots (and every
        # committed golden) stay byte-identical
        learning = None
        if self._lens_rows:
            from fedml_tpu.obs import lens as _lens

            try:
                learning = _lens.fold_rows(self._lens_rows,
                                           _lens.lens_topk())
            except Exception:
                learning = None
            self._lens_rows = []
            if learning is not None and profile is not None:
                # the watchdog's attribution rules read the suspects from
                # the profile view it is handed (same round, same fold)
                profile["lens"] = learning

        events: list = []
        health = None
        if self.watchdog is not None:
            events = self.watchdog.check_round(
                round_idx, loss=loss, round_ms=round_ms, wire=wire_view,
                profile=profile)
            health = {"state": self.watchdog.state, "events": events}
            _SESSION["critical"] += sum(
                1 for e in events if e["severity"] == "critical")
            tr = tracer_if_enabled(0)
            if tr is not None:
                for ev in events:
                    tr.instant("health", cat="health", args=dict(ev))

        rates = None
        if self._t_last_ms is not None and now_ms > self._t_last_ms:
            dt_s = (now_ms - self._t_last_ms) / 1e3
            rates = {"rounds_per_s": round(1.0 / dt_s, 4)}
            if n_cohort:
                rates["clients_per_s"] = round(n_cohort / dt_s, 2)
        self._t_last_ms = now_ms

        snap = {"v": 1, "ts_ms": int(now_ms), "round": int(round_idx),
                "source": source, "loss": loss,
                "round_ms": _round_num(round_ms), "cohort": n_cohort,
                "rates": rates, "lanes": lanes, "stage": stage,
                "profile": profile, "sketches": sketches,
                "cost": self._cost(round_ms), "health": health}
        if learning is not None:
            snap["learning"] = learning
        if self.exporter is not None:
            self.exporter.emit(snap)
        # fedflight: retain the round in the recorder's window AND — when
        # this round's criticals are about to escalate below — dump the
        # incident bundle BEFORE maybe_escalate raises, so the bundle
        # exists by the time FederationHealthError propagates
        rec = _flight_recorder()
        if rec is not None:
            rec.record_round(snap, watchdog=self.watchdog,
                             tenant=self.tenant, events=events)
        if self.watchdog is not None:
            self.watchdog.maybe_escalate(events)
        return snap

    def _cost(self, round_ms: Optional[float]) -> Optional[dict]:
        """fedcost join: the FLOP-dominant attributed program against this
        round's measured wall (1 invocation/round — exact for the default
        one-program-per-round schedules)."""
        from fedml_tpu.obs import cost as _cost

        if not round_ms or not _cost.cost_attribution_enabled():
            return None
        tables = _cost.cost_tables()
        if not tables:
            return None
        rec = max(tables.values(),
                  key=lambda r: r["summary"]["gemm_flops_per_invocation"])
        if not self._peak_resolved:
            try:
                import jax

                self._peak = _cost.peak_flops(jax.devices()[0])[0]
            except Exception:  # pragma: no cover - devices always queryable
                self._peak = None
            self._peak_resolved = True
        rf = _cost.roofline(rec["summary"], round_ms / 1e3, invocations=1,
                            peak=self._peak)
        return {"program": rec["program"],
                "out_lane_ceiling": rec["summary"].get("out_lane_ceiling"),
                "achieved_gflops_per_sec": rf["achieved_gflops_per_sec"],
                "mfu_mac": rf["mfu_mac"],
                "mfu_vs_ceiling": rf.get("mfu_vs_ceiling")}

    def aggregates(self, round_idx: Optional[int] = None) -> Optional[dict]:
        """End-of-run profiler aggregates (the bench JSON tail block)."""
        return (self.profiler.aggregates(round_idx)
                if self.profiler is not None else None)

    def close(self) -> None:
        if self.exporter is not None:
            self.exporter.close()
            self.exporter = None


# -- process-wide hub --------------------------------------------------------

_PLANE: Optional[PulsePlane] = None

#: per-thread plane override (plane_scope): the gateway runs each tenant's
#: handler lane on its own thread under a scope, so the lane's round
#:  boundaries pulse into that tenant's OWN stream/watchdog while the
#: process-wide plane (if any) keeps serving everything else.
_TLS = threading.local()


def pulse_if_enabled() -> Optional[PulsePlane]:
    """Hot-path gate: ``None`` while the pulse plane is off — a thread-local
    attribute read plus one global read, no allocation — else the calling
    thread's scoped plane (``plane_scope``) or the process-wide one."""
    plane = getattr(_TLS, "plane", None)
    return plane if plane is not None else _PLANE


@contextlib.contextmanager
def plane_scope(plane: Optional[PulsePlane]):
    """Route this THREAD's ``pulse_if_enabled()`` to ``plane`` for the
    duration of the block (previous override restored on exit). Other
    threads keep the process-wide plane."""
    prev = getattr(_TLS, "plane", None)
    _TLS.plane = plane
    try:
        yield plane
    finally:
        _TLS.plane = prev


def pulse_enabled() -> bool:
    return _PLANE is not None


def configure(path: Optional[str] = None,
              prometheus_dir: Optional[str] = None, *,
              profile_store: Optional[bool] = None,
              capacity_hint: int = 1024, sketch_alpha: float = 0.01,
              loss_limit: float = 0.0,
              stall_sec: Optional[float] = None, stale_spike: int = 8,
              skew: float = 4.0, version_lag: float = 0.0,
              update_norm: float = 0.0, drift: float = 0.0,
              escalate: bool = False) -> Optional[PulsePlane]:
    """(Re)build the process-wide plane. ``configure(None)`` disables it;
    ``configure(None, profile_store=True)`` builds a profiler-only plane
    with no stream (the bench's mode). Returns the plane (or None)."""
    global _PLANE
    if _PLANE is not None:
        _PLANE.close()
        _PLANE = None
    if profile_store is None:
        profile_store = bool(path)
    if not path and not profile_store:
        return None
    exporter = LiveExporter(path, prometheus_dir) if path else None
    profiler = (ClientProfiler(capacity_hint=capacity_hint,
                               sketch_alpha=sketch_alpha)
                if profile_store else None)
    watchdog = HealthWatchdog(loss_limit=loss_limit, stall_sec=stall_sec,
                              stale_spike=stale_spike, skew=skew,
                              version_lag=version_lag,
                              update_norm=update_norm, drift=drift,
                              escalate=escalate)
    # delta rules start from the registry's CURRENT totals: an earlier
    # federation's wire anomalies in this process are not this run's
    watchdog.baseline(default_registry().snapshot("wire"))
    _PLANE = PulsePlane(exporter=exporter, profiler=profiler,
                        watchdog=watchdog)
    if exporter is not None:
        _SESSION["runs"] += 1
    return _PLANE


_NO_PULSE = object()


def configure_from(config) -> bool:
    """Configure from a FedConfig-shaped object (chained from
    ``tracer.configure_from`` so every entry point makes the one call).
    Same semantics as the tracer: ``pulse_path`` is authoritative — unset
    DISABLES a plane left on by an earlier run in the process; only a
    config without the attribute at all leaves the plane untouched."""
    # the lens arms from its own flag, not pulse_path: chained FIRST so
    # --lens on is honored by every entry point even when no pulse stream
    # is configured (the fedlint config-flag-drift contract)
    from fedml_tpu.obs import lens as _lens

    _lens.configure_from(config)
    path = getattr(config, "pulse_path", _NO_PULSE)
    if path is _NO_PULSE:
        return pulse_enabled()
    if not path:
        if pulse_enabled():
            configure(None)
        return False
    configure(path,
              prometheus_dir=getattr(config, "pulse_prometheus_dir", None),
              sketch_alpha=getattr(config, "sketch_alpha", 0.01),
              loss_limit=getattr(config, "health_loss_limit", 0.0),
              stall_sec=getattr(config, "health_stall_sec", None),
              stale_spike=getattr(config, "health_stale_spike", 8),
              skew=getattr(config, "health_skew", 4.0),
              version_lag=getattr(config, "health_version_lag", 0.0),
              update_norm=getattr(config, "health_update_norm", 0.0),
              drift=getattr(config, "health_drift", 0.0),
              escalate=getattr(config, "health_escalate", False))
    return True


def reset() -> None:
    """Close and drop the plane (tests; never mid-run)."""
    configure(None)


def session_stats() -> dict:
    """Process-lifetime pulse stats (the conftest session summary)."""
    return dict(_SESSION)
