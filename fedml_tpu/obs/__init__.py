"""fedtrace: span tracing + unified metrics registry (DESIGN.md §12).

The paper's observability story is rank-0 wandb scalars plus ad-hoc
wall-clock pairs; this package is the reproduction's replacement — the
timing instrumentation FedJAX ships built-in (arXiv:2108.02117) and the
cross-rank visibility FedML Parrot's heterogeneity-aware scheduling
assumes (arXiv:2303.01778):

- :mod:`fedml_tpu.obs.registry` — one process-wide
  :class:`MetricsRegistry`; every counter surface in the tree
  (``RoundTimer`` phase sums, the reliable/chaos wire counters, pipeline
  stage rows) is a :class:`CounterGroup` attached to it, so the existing
  public APIs become *views* over one store instead of four disjoint dicts.
- :mod:`fedml_tpu.obs.tracer` — per-rank span tracer: monotonic
  durations, ring-buffered events, allocation-free when disabled. Trace
  context piggybacks on ``comm/message.py`` envelopes so send spans stitch
  to recv spans across ranks and transports by message id.
- :mod:`fedml_tpu.obs.export` — Perfetto/Chrome ``trace_event`` JSON and
  JSONL exporters; ``tools/trace_report.py`` is the analyzer.

Tracing is OFF by default and enabled per run via ``--trace_dir``
(core/config.py). The contract: a traced run is bit-identical to an
untraced run — the tracer only ever reads clocks.
"""

from fedml_tpu.obs.registry import (
    CounterGroup,
    MetricsRegistry,
    default_registry,
)
from fedml_tpu.obs.tracer import (
    Tracer,
    configure,
    configure_from,
    flush_all,
    get_tracer,
    reset,
    tracer_if_enabled,
    tracing_enabled,
)

__all__ = [
    "CounterGroup",
    "MetricsRegistry",
    "Tracer",
    "configure",
    "configure_from",
    "default_registry",
    "flush_all",
    "get_tracer",
    "reset",
    "tracer_if_enabled",
    "tracing_enabled",
]
