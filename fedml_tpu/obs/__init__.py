"""fedtrace: span tracing + unified metrics registry (DESIGN.md §12).

The paper's observability story is rank-0 wandb scalars plus ad-hoc
wall-clock pairs; this package is the reproduction's replacement — the
timing instrumentation FedJAX ships built-in (arXiv:2108.02117) and the
cross-rank visibility FedML Parrot's heterogeneity-aware scheduling
assumes (arXiv:2303.01778):

- :mod:`fedml_tpu.obs.registry` — one process-wide
  :class:`MetricsRegistry`; every counter surface in the tree
  (``RoundTimer`` phase sums, the reliable/chaos wire counters, pipeline
  stage rows) is a :class:`CounterGroup` attached to it, so the existing
  public APIs become *views* over one store instead of four disjoint dicts.
- :mod:`fedml_tpu.obs.tracer` — per-rank span tracer: monotonic
  durations, ring-buffered events, allocation-free when disabled. Trace
  context piggybacks on ``comm/message.py`` envelopes so send spans stitch
  to recv spans across ranks and transports by message id.
- :mod:`fedml_tpu.obs.export` — Perfetto/Chrome ``trace_event`` JSON and
  JSONL exporters; ``tools/trace_report.py`` is the analyzer.
- :mod:`fedml_tpu.obs.compile` (fedscope) — per-program compile telemetry:
  LRU hit/miss counters plus build / first-call spans, so compile-vs-execute
  time is a first-class, regression-testable metric.
- :mod:`fedml_tpu.obs.device` (fedscope) — device-memory sampler at round
  boundaries; a "devices" counter lane in the Perfetto export without a
  separate ``--profile_dir`` profiler run.
- :mod:`fedml_tpu.obs.cost` (fedcost) — static per-op roofline
  attribution: every round program built through ``timed_build`` can be
  lowered to HLO and read back as a GEMM table (M/K/N, FLOPs, MXU lane
  fills) with a flop-weighted lane ceiling per program; also the single
  shared peak-FLOPs table behind every MFU number.

Tracing is OFF by default and enabled per run via ``--trace_dir``
(core/config.py). The contract: a traced run is bit-identical to an
untraced run — the tracer only ever reads clocks.
"""

from fedml_tpu.obs.compile import compile_counters, record_cache_hit, timed_build
from fedml_tpu.obs.cost import (
    cost_attribution_enabled,
    cost_tables,
    enable_cost_attribution,
    fwd_flops_per_image,
    peak_flops,
    reset_cost_tables,
)
from fedml_tpu.obs.device import sample_device_memory
from fedml_tpu.obs.registry import (
    CounterGroup,
    MetricsRegistry,
    default_registry,
)
from fedml_tpu.obs.tracer import (
    Tracer,
    configure,
    configure_from,
    flush_all,
    get_tracer,
    reset,
    set_process_index,
    trace_filename,
    tracer_if_enabled,
    tracing_enabled,
)

__all__ = [
    "CounterGroup",
    "MetricsRegistry",
    "Tracer",
    "compile_counters",
    "configure",
    "configure_from",
    "cost_attribution_enabled",
    "cost_tables",
    "default_registry",
    "enable_cost_attribution",
    "fwd_flops_per_image",
    "peak_flops",
    "reset_cost_tables",
    "flush_all",
    "get_tracer",
    "record_cache_hit",
    "reset",
    "sample_device_memory",
    "set_process_index",
    "timed_build",
    "trace_filename",
    "tracer_if_enabled",
    "tracing_enabled",
]
