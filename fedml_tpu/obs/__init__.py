"""fedtrace: span tracing + unified metrics registry (DESIGN.md §12).

The paper's observability story is rank-0 wandb scalars plus ad-hoc
wall-clock pairs; this package is the reproduction's replacement — the
timing instrumentation FedJAX ships built-in (arXiv:2108.02117) and the
cross-rank visibility FedML Parrot's heterogeneity-aware scheduling
assumes (arXiv:2303.01778):

- :mod:`fedml_tpu.obs.registry` — one process-wide
  :class:`MetricsRegistry`; every counter surface in the tree
  (``RoundTimer`` phase sums, the reliable/chaos wire counters, pipeline
  stage rows) is a :class:`CounterGroup` attached to it, so the existing
  public APIs become *views* over one store instead of four disjoint dicts.
- :mod:`fedml_tpu.obs.tracer` — per-rank span tracer: monotonic
  durations, ring-buffered events, allocation-free when disabled. Trace
  context piggybacks on ``comm/message.py`` envelopes so send spans stitch
  to recv spans across ranks and transports by message id.
- :mod:`fedml_tpu.obs.export` — Perfetto/Chrome ``trace_event`` JSON and
  JSONL exporters; ``tools/trace_report.py`` is the analyzer.
- :mod:`fedml_tpu.obs.compile` (fedscope) — per-program compile telemetry:
  LRU hit/miss counters plus build / first-call spans, so compile-vs-execute
  time is a first-class, regression-testable metric.
- :mod:`fedml_tpu.obs.device` (fedscope) — device-memory sampler at round
  boundaries; a "devices" counter lane in the Perfetto export without a
  separate ``--profile_dir`` profiler run.
- :mod:`fedml_tpu.obs.cost` (fedcost) — static per-op roofline
  attribution: every round program built through ``timed_build`` can be
  lowered to HLO and read back as a GEMM table (M/K/N, FLOPs, MXU lane
  fills) with a flop-weighted lane ceiling per program; also the single
  shared peak-FLOPs table behind every MFU number.
- :mod:`fedml_tpu.obs.profile` / :mod:`fedml_tpu.obs.live` /
  :mod:`fedml_tpu.obs.health` (fedpulse) — the LIVE plane: a bounded
  array-backed per-client profile store (EMA train-ms, upload bytes,
  participation, staleness — the signals cohort scheduling and FedBuff
  weighting consume), a ``pulse.jsonl`` streaming exporter of atomic
  round-boundary snapshots (registry lanes, profiler aggregates, cost
  MFU) with an optional Prometheus textfile mirror, and a rule-driven
  health watchdog (NaN/divergent loss, round stall, ``gave_up``/
  ``stale_uploads`` spikes, straggler skew) with an escalate-to-raise
  mode. ``tools/fedtop.py`` tails the stream live.
- :mod:`fedml_tpu.obs.sketch` (fedsketch) — fixed-memory, mergeable
  log-bucketed distribution sketches (~1% relative error, exact
  order-independent merge, compact JSON codec) behind the profiler's
  train-ms / upload-latency / payload-bytes / staleness percentile lanes;
  paired with the tracer's deterministic head-based round sampling
  (``--trace_sample_rate``, a pure function of (seed, round, id)) so
  thousand-client cohorts keep bounded spans while sampled-out rounds
  still feed every sketch.
- :mod:`fedml_tpu.obs.flight` (fedflight, DESIGN.md §21) — the black-box
  recorder: while ``--flight_dir`` is armed, a second per-rank FULL-rate
  span ring (sampled-out rounds included, via a shadow tracer), per-scope
  pulse-snapshot windows and watchdog transitions are retained for the
  last ``--flight_window`` rounds; watchdog escalation (dump BEFORE the
  raise), gateway quarantine, peer death, or SIGUSR2 dumps a
  self-contained ``incident-<id>/`` bundle whose id is pure in
  ``(seed, round, rule)`` — every rank converges on one bundle with no
  coordination. ``tools/fedpost.py`` renders the postmortem verdict.

Tracing is OFF by default and enabled per run via ``--trace_dir``
(core/config.py); the pulse plane likewise via ``--pulse_path``. The
contract: a traced or pulsed run is bit-identical to a plain run — these
modules only ever read clocks and counters.
"""

from fedml_tpu.obs.compile import compile_counters, record_cache_hit, timed_build
from fedml_tpu.obs.cost import (
    cost_attribution_enabled,
    cost_tables,
    enable_cost_attribution,
    fwd_flops_per_image,
    peak_flops,
    reset_cost_tables,
)
from fedml_tpu.obs.device import sample_device_memory
from fedml_tpu.obs.flight import (
    FlightRecorder,
    flight_enabled,
    incident_id,
    recorder_if_enabled,
)
from fedml_tpu.obs.health import FederationHealthError, HealthWatchdog
from fedml_tpu.obs.live import (
    LiveExporter,
    PulsePlane,
    plane_scope,
    pulse_enabled,
    pulse_if_enabled,
)
from fedml_tpu.obs.profile import ClientProfiler
from fedml_tpu.obs.registry import (
    CounterGroup,
    MetricsRegistry,
    default_registry,
    registry_scope,
)
from fedml_tpu.obs.sketch import Sketch, merge_all
from fedml_tpu.obs.tracer import (
    Tracer,
    configure,
    configure_from,
    flush_all,
    get_tracer,
    reset,
    set_process_index,
    span_sampled,
    trace_filename,
    tracer_if_enabled,
    tracer_if_sampled,
    tracing_enabled,
)

__all__ = [
    "ClientProfiler",
    "CounterGroup",
    "FederationHealthError",
    "FlightRecorder",
    "HealthWatchdog",
    "LiveExporter",
    "MetricsRegistry",
    "PulsePlane",
    "Sketch",
    "Tracer",
    "compile_counters",
    "configure",
    "configure_from",
    "cost_attribution_enabled",
    "cost_tables",
    "default_registry",
    "enable_cost_attribution",
    "flight_enabled",
    "fwd_flops_per_image",
    "incident_id",
    "merge_all",
    "peak_flops",
    "reset_cost_tables",
    "flush_all",
    "get_tracer",
    "plane_scope",
    "pulse_enabled",
    "pulse_if_enabled",
    "record_cache_hit",
    "recorder_if_enabled",
    "registry_scope",
    "reset",
    "sample_device_memory",
    "set_process_index",
    "span_sampled",
    "timed_build",
    "trace_filename",
    "tracer_if_enabled",
    "tracer_if_sampled",
    "tracing_enabled",
]
