"""Trace exporters: JSONL (native) and Perfetto/Chrome ``trace_event``.

The native on-disk format is one JSON object per line (what
``Tracer.flush`` appends): ``ph`` is the event kind — ``X`` complete span,
``i`` instant, ``C`` counter, ``O`` unclosed-at-flush span, ``M`` file
metadata. :func:`to_chrome_trace` converts a merged multi-rank event list
into the ``trace_event`` JSON that Perfetto / ``chrome://tracing`` loads
directly: (process, rank) -> ``pid`` (one process track per rank; per-host
files from a jax.distributed run carry a ``proc`` tag and get their own
track block), thread -> ``tid``, and every send/recv span pair linked by
message uid becomes a flow arrow (``ph: s``/``f``) so the cross-rank
causal chain is drawn, not inferred. The fedscope device-memory sampler's
``device``-category counters are routed to a dedicated "devices" track so
the HBM lane sits apart from the span timeline.
"""

from __future__ import annotations

import json
from typing import Iterable, Optional

#: base pid of the dedicated counter tracks for device-category samples
#: (one per host: pid = DEVICE_LANE_PID - proc). Negative, so the lanes
#: can never collide with the non-negative (proc, rank) span pids no
#: matter how many hosts/ranks a run has; Perfetto treats pid as an
#: opaque int64, so negative track ids render fine.
DEVICE_LANE_PID = -1
#: per-host pid stride: pid = proc * stride + rank (ranks stay < stride)
_PROC_PID_STRIDE = 100_000


def read_jsonl(path: str) -> list[dict]:
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


def write_jsonl(path: str, events: Iterable[dict]) -> None:
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _mid(ev: dict) -> Optional[str]:
    return (ev.get("args") or {}).get("mid")


def to_chrome_trace(events: Iterable[dict]) -> dict:
    """Convert merged per-rank events into a ``trace_event`` JSON object
    (``{"traceEvents": [...]}``). Metadata lines become process_name
    entries; send->recv message uids become flow events."""
    out = []
    seen_pids = set()
    device_lanes_named = set()
    sends: dict[str, dict] = {}
    recvs: dict[str, dict] = {}
    for ev in events:
        ph = ev.get("ph")
        rank = int(ev.get("rank", 0))
        proc = int(ev.get("proc", 0))
        pid = proc * _PROC_PID_STRIDE + rank
        if pid not in seen_pids:
            seen_pids.add(pid)
            label = f"p{proc} rank {rank}" if proc else f"rank {rank}"
            out.append({"ph": "M", "name": "process_name", "pid": pid,
                        "args": {"name": label}})
        base = {"name": ev.get("name"), "cat": ev.get("cat", "app"),
                "ts": ev.get("ts", 0), "pid": pid,
                "tid": ev.get("tid", 0)}
        ev_args = dict(ev.get("args") or {})
        if ph == "X":
            out.append({**base, "ph": "X", "dur": ev.get("dur", 0),
                        "args": ev_args})
            m = ev_args.get("mid")
            if m is not None:
                (sends if ev.get("name") == "send" else recvs)[m] = ev
        elif ph == "i":
            out.append({**base, "ph": "i", "s": "t", "args": ev_args})
        elif ph == "C":
            vals = ev_args.get("values") or {}
            if ev.get("cat") == "device":
                # the device-memory sampler gets its own counter lane —
                # one PER HOST: merged multi-host traces repeat the same
                # series keys (d0/..., host/rss_bytes), and a shared track
                # would interleave unrelated hosts into one sawtooth
                lane_pid = DEVICE_LANE_PID - proc
                if lane_pid not in device_lanes_named:
                    device_lanes_named.add(lane_pid)
                    label = f"devices p{proc}" if proc else "devices"
                    out.append({"ph": "M", "name": "process_name",
                                "pid": lane_pid, "args": {"name": label}})
                base = {**base, "pid": lane_pid}
            # Chrome counter events take flat numeric args
            out.append({**base, "ph": "C",
                        "args": {k: v for k, v in vals.items()
                                 if isinstance(v, (int, float))}})
        elif ph == "O":
            # unclosed span: render as a zero-length instant flagged
            out.append({**base, "ph": "i", "s": "p",
                        "args": {**ev_args, "unclosed": True}})
    # flow arrows: one per (send, recv) pair sharing a message uid
    def _pid(ev):
        return int(ev.get("proc", 0)) * _PROC_PID_STRIDE + int(ev.get("rank", 0))

    for m, s in sends.items():
        r = recvs.get(m)
        if r is None:
            continue
        flow = {"name": "msg", "cat": "comm", "id": _flow_id(m)}
        out.append({**flow, "ph": "s", "ts": s.get("ts", 0),
                    "pid": _pid(s), "tid": s.get("tid", 0)})
        out.append({**flow, "ph": "f", "bp": "e",
                    "ts": r.get("ts", 0) + int(r.get("dur", 0) or 0),
                    "pid": _pid(r), "tid": r.get("tid", 0)})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def _flow_id(mid: str) -> int:
    # trace_event flow ids are integers; fold the hex uid down
    return int(mid[:12], 16) if mid else 0


def write_chrome_trace(path: str, events: Iterable[dict]) -> None:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(events), f)
