"""fedlens: in-program learning-signal telemetry with per-client attribution.

The observability plane to date watches only *systems* signals — time,
wire, MFU — and the watchdog's model-quality rules are scalar
(``nan_loss``/``divergent_loss`` on the round-mean loss), so a single
poisoned or diverging client is invisible until it wrecks the global
model. The lens closes that gap with three per-client learning signals
computed INSIDE the round programs, as cheap reductions over values the
round already materializes (no second pass over params, no extra host
sync):

- ``update_norm`` — L2 norm of the client's raw local update
  (post-training params minus the broadcast params, f32);
- ``loss_delta`` — first-epoch mean loss minus last-epoch mean loss
  (positive = the client's local training is still making progress;
  zero by construction when ``epochs == 1``);
- ``align`` — cosine of the client's raw update against the
  counts-weighted mean update of the round cohort (the fedavg
  pseudo-gradient). The exported ``drift`` lane is ``1 - align``
  (0 = perfectly aligned, 1 = orthogonal, 2 = anti-aligned).

The alignment basis is deliberately the RAW weighted-mean update — not
the post-``client_transform`` aggregate — so a robust-aggregation clip
cannot hide the attacker from the very telemetry meant to catch it, and
the definition is identical across the vmap, gather, grouped and packed
round forms (the packed-vs-vmap parity test pins it at fedseg
tolerance).

Contracts (the tracer/pulse discipline, restated):

- **off by default, one-global-read gate**: :func:`lens_enabled` is a
  dict read; disabled call sites build the exact round programs they
  always built (lens-ON adds output-only reductions, and the pinned
  bit-identity tests hold lens-on == lens-off weights on sim and the
  4-rank grpc harness);
- **no host sync on async rounds**: the armed sim APIs stash the round's
  lens DEVICE arrays and convert one round late under
  ``--async_rounds`` (see ``FedAvgAPI._pulse_lens``);
- **attribution, not just detection**: every consumer — the pulse
  ``learning`` block, the three watchdog rules, the fedflight bundle,
  fedpost/fedtop — carries the top-k suspect *logical client ids*.

Privacy note: suspect ids are LOGICAL ids (the federation's own client
index space). The lens exports norms/cosines/loss scalars only — never
update contents — but a per-client scalar stream is still a membership
side channel; deployments that treat client identity as sensitive
should leave ``--lens off`` (the default) or strip the ``learning``
block before shipping pulse streams off-box (docs/DESIGN.md §22).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = [
    "ANTI_ALIGN", "LENS_LANES", "configure", "configure_from", "fold_rows",
    "host_lens_stats", "lens_enabled", "lens_topk", "packed_lens",
    "rank_suspects", "reset", "session_stats", "stacked_lens",
]

#: process-lifetime stats for the conftest ``[t1] lens:`` session line
#: (NEVER reset — they describe the session, not one run)
_SESSION = {"folds": 0, "clients": 0, "suspects": 0}

#: the lens's two ClientProfiler sketch lanes (per-round deltas feed the
#: watchdog's update_norm_spike / client_drift rules)
LENS_LANES = ("update_norm", "drift")

#: cosine at or below which an update counts as anti-aligned with the
#: round aggregate — the aligned_suspects signature (drift >= 1.2)
ANTI_ALIGN = -0.2

_EPS = 1e-12

_STATE = {"on": False, "topk": 5}


def lens_enabled() -> bool:
    """Hot-path gate: one dict read; False = every builder compiles the
    exact lens-free program it always did."""
    return _STATE["on"]


def lens_topk() -> int:
    return _STATE["topk"]


def configure(on: bool = False, topk: int = 5) -> None:
    """Arm/disarm the lens process-wide. Arm BEFORE building an API (the
    round programs snapshot the flag at first trace, like the tracer)."""
    _STATE["on"] = bool(on)
    _STATE["topk"] = max(int(topk or 5), 1)


_NO_LENS = object()


def configure_from(config) -> bool:
    """Configure from a FedConfig-shaped object (chained from
    ``live.configure_from`` so every entry point makes the one call).
    ``lens`` is authoritative when present: ``"off"`` disarms a lens left
    on by an earlier run in the process; a config without the attribute
    leaves the state untouched (direct ``configure()`` callers)."""
    mode = getattr(config, "lens", _NO_LENS)
    if mode is _NO_LENS:
        return lens_enabled()
    configure(str(mode) == "on",
              topk=int(getattr(config, "lens_topk", 5) or 5))
    return lens_enabled()


def reset() -> None:
    configure(False)


def session_stats() -> dict:
    """Process-lifetime lens stats (the conftest ``[t1] lens:`` session
    line): round folds performed, client observations folded, suspects
    ranked."""
    return dict(_SESSION)


# -- device-side helpers (jit-pure; imported inside round builders) ----------

def stacked_lens(variables0, res, weights) -> dict:
    """Full lens dict from a stacked cohort result (the vmap / gather /
    grouped round forms): ``res.variables`` leaves are ``[cohort, ...]``.
    Returns ``{"update_norm", "align"[, "loss_delta"]}``, each
    ``[cohort]`` f32. Pure output-only reductions: nothing here feeds the
    aggregate, so an armed program computes bit-identical weights."""
    import jax
    import jax.numpy as jnp

    f32 = jnp.float32
    upd = jax.tree.leaves(jax.tree.map(
        lambda s, v: s.astype(f32) - v.astype(f32)[None],
        res.variables["params"], variables0["params"]))
    n = upd[0].shape[0]
    flat = [u.reshape((n, -1)) for u in upd]
    n2 = sum(jnp.sum(u * u, axis=1) for u in flat)
    w = jnp.asarray(weights, f32)
    tot = jnp.maximum(jnp.sum(w), _EPS)
    mean = [jnp.tensordot(w / tot, u, axes=1) for u in flat]
    m2 = sum(jnp.sum(m * m) for m in mean)
    dots = sum(jnp.tensordot(u, m, axes=1) for u, m in zip(flat, mean))
    norm = jnp.sqrt(n2)
    out = {"update_norm": norm,
           "align": dots / jnp.maximum(norm * jnp.sqrt(m2), _EPS)}
    first = getattr(res, "first_loss", None)
    if first is not None:
        out["loss_delta"] = first.astype(f32) - res.train_loss.astype(f32)
    return out


def packed_lens(upd_stack, l_first, l_last, member_w) -> dict:
    """Full lens dict from the packed forms' emitted member stacks:
    ``upd_stack`` leaves carry the member axes in front (``[L, k, ...]``
    joint/lane form), ``member_w`` has exactly those axes. Same
    definitions as :func:`stacked_lens` — the alignment basis is the
    member-weighted mean of the raw emitted updates — so packed and vmap
    agree to accumulation-order tolerance. All outputs are flattened to
    one member axis in ``member_pos`` order (host side maps them back to
    logical ids)."""
    import jax
    import jax.numpy as jnp

    f32 = jnp.float32
    n = int(np.prod(member_w.shape))
    flat = [u.astype(f32).reshape((n, -1))
            for u in jax.tree.leaves(upd_stack)]
    n2 = sum(jnp.sum(u * u, axis=1) for u in flat)
    w = member_w.astype(f32).reshape(-1)
    tot = jnp.maximum(jnp.sum(w), _EPS)
    mean = [jnp.tensordot(w / tot, u, axes=1) for u in flat]
    m2 = sum(jnp.sum(m * m) for m in mean)
    dots = sum(jnp.tensordot(u, m, axes=1) for u, m in zip(flat, mean))
    norm = jnp.sqrt(n2)
    return {"update_norm": norm,
            "align": dots / jnp.maximum(norm * jnp.sqrt(m2), _EPS),
            "loss_delta": (l_first - l_last).astype(f32).reshape(-1)}


# -- host-side helpers (edge servers; numpy trees) ---------------------------

def host_lens_stats(variables0, member_trees, aggregate=None) -> dict:
    """Edge-server lens over host numpy trees: per-member raw-update L2
    norms, plus cosine vs the aggregate's update when the server still
    holds one (the batch aggregator; the O(1) streaming fold keeps
    norm-only — it never buffers the per-member trees an alignment basis
    needs). The aggregate is the counts-weighted mean of member params, so
    ``aggregate - variables0`` IS the weighted-mean raw update — the same
    alignment basis the device paths use."""
    import jax

    def flat(t):
        return np.concatenate([np.asarray(l, np.float64).ravel()
                               for l in jax.tree.leaves(t)])

    base = flat(variables0)
    ups = [flat(t) - base for t in member_trees]
    norm = np.array([np.linalg.norm(u) for u in ups], np.float64)
    out = {"update_norm": norm, "align": None}
    if aggregate is not None:
        m = flat(aggregate) - base
        mn = float(np.linalg.norm(m))
        out["align"] = np.array(
            [float(u @ m) / max(float(n) * mn, _EPS)
             for u, n in zip(ups, norm)], np.float64)
    return out


# -- host-side folding / ranking ---------------------------------------------

def _broadcast(v, ids: np.ndarray) -> Optional[np.ndarray]:
    if v is None:
        return None
    return np.broadcast_to(np.asarray(v, np.float64), ids.shape).astype(
        np.float64)


def fold_rows(rows: list, k: int) -> dict:
    """Merge one round's lens feed rows (sim stash + edge per-upload
    stats) into the pulse snapshot's ``learning`` block: client count and
    the ranked top-``k`` suspects. A client observed twice in one round
    (a re-upload) keeps its worst (highest-drift, then highest-norm)
    observation."""
    ids = np.concatenate([r["ids"] for r in rows])
    norm = np.concatenate([_broadcast(r["update_norm"], r["ids"])
                           for r in rows])
    align = (np.concatenate(
        [(_broadcast(r.get("align"), r["ids"])
          if r.get("align") is not None
          else np.full(r["ids"].shape, np.nan)) for r in rows]))
    delta = (np.concatenate(
        [(_broadcast(r.get("loss_delta"), r["ids"])
          if r.get("loss_delta") is not None
          else np.full(r["ids"].shape, np.nan)) for r in rows]))
    out = {"clients": int(np.unique(ids).size),
           "suspects": rank_suspects(ids, norm, align, delta, k)}
    _SESSION["folds"] += 1
    _SESSION["clients"] += out["clients"]
    _SESSION["suspects"] += len(out["suspects"])
    return out


def rank_suspects(ids, norm, align, loss_delta, k: int) -> list:
    """Deterministic suspicion ranking: drift (descending) first — an
    anti-aligned update is the strongest poison signal — then update norm
    (descending), then id (ascending) so ties never reorder between runs.
    Clients without an alignment basis (edge streaming folds) rank by
    norm among themselves, below any drifting client."""
    ids = np.asarray(ids, np.int64)
    norm = np.asarray(norm, np.float64)
    align = np.asarray(align, np.float64)
    delta = np.asarray(loss_delta, np.float64)
    drift = np.where(np.isnan(align), -np.inf, 1.0 - align)
    # lexsort: LAST key is primary
    order = np.lexsort((ids, -norm, -drift))
    out, seen = [], set()
    for j in order:
        cid = int(ids[j])
        if cid in seen:
            continue
        seen.add(cid)
        s = {"client": cid, "norm": round(float(norm[j]), 6)}
        if np.isfinite(align[j]):
            s["align"] = round(float(align[j]), 6)
            s["drift"] = round(float(drift[j]), 6)
        if np.isfinite(delta[j]):
            s["loss_delta"] = round(float(delta[j]), 6)
        out.append(s)
        if len(out) >= int(k):
            break
    return out
