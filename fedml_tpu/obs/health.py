"""Federation health watchdog: rule-driven round-boundary checks.

The trace stack answers "where did the time go" after the run; nothing in
the tree answers "is this federation healthy RIGHT NOW" while it serves —
the question a long-lived multi-tenant gateway (ROADMAP item 4) and any
unattended cross-device run needs. :class:`HealthWatchdog` closes that gap
with a fixed rule set evaluated at every round boundary, over signals the
round already produces (no extra syncs, no device reads):

==================  ========  =============================================
rule                severity  fires when
==================  ========  =============================================
``nan_loss``        critical  the round loss is NaN/inf (always armed)
``divergent_loss``  critical  loss exceeds ``--health_loss_limit`` (>0)
``round_stall``     critical  the round wall exceeds ``--health_stall_sec``
``gave_up``         critical  the wire ``gave_up`` counter moved this round
                              (a message was abandoned after retry
                              exhaustion — data loss, always armed)
``stale_spike``     warn      ``stale_uploads`` grew by at least
                              ``--health_stale_spike`` this round (late
                              retransmits of deadline-closed rounds piling
                              up — the chaos/straggler signature)
``peer_dead``       warn      the wire ``peer_dead`` counter moved this
                              round — a peer exhausted a message's full
                              retry budget for the FIRST time (the reliable
                              layer's dead-peer oracle, counted once per
                              peer, always armed). Every edge paradigm
                              surfaces dead workers here, not just the
                              fedbuff ejection hook.
``straggler_skew``  warn      THIS round's train-ms sketch delta has
                              p99/p50 over ``--health_skew`` (>= 4 seen
                              clients; the pulse plane feeds the per-round
                              delta, so a compile-heavy round 0 can never
                              own a later round's p99; falls back to the
                              EMA p95/p50 spread when a profile predates
                              the sketch lanes or the round holds < 32
                              samples — a smaller tail is rank noise) —
                              tail ratio, not mean ranking, so one
                              pathological straggler in a 10k cohort still
                              fires it
``profiles_dropped``  warn    the profiler dropped client ids past its
                              ``max_clients`` cap this round — the store is
                              silently blind to part of the cohort (raise
                              the cap or fix the id space)
``version_lag``     warn      THIS round's staleness-sketch delta p99 (the
                              per-contribution versions-behind lane fedbuff
                              writes) reaches ``--health_version_lag``;
                              ESCALATES TO CRITICAL when the p99 has grown
                              strictly monotonically for
                              :data:`VERSION_LAG_MONOTONIC_N` consecutive
                              snapshots that carry the lane — clients
                              falling ever further behind the emitted
                              version is the buffered-async divergence
                              signature (a bounded-but-high lag is a warn;
                              an unbounded one means the staleness decay
                              is no longer keeping the fold mass current)
``update_norm_spike``  warn   fedlens: THIS round's update-norm sketch
                              delta p99 reaches ``--health_update_norm``
                              (>0 arms it) — some client pushed an update
                              far outside the cohort's norm envelope; the
                              event carries the round's top-k suspect ids
``client_drift``    warn      fedlens: THIS round's drift sketch delta p99
                              (1 - cosine vs the round aggregate) reaches
                              ``--health_drift`` (>0 arms it) — part of
                              the cohort is pulling away from the
                              federation's direction; carries suspect ids
``aligned_suspects``  critical  fedlens (always armed when the lens is on):
                              a ranked suspect is ANTI-aligned (cosine <=
                              ``lens.ANTI_ALIGN``) with an update norm at
                              or above this round's cohort median — the
                              opposite-direction-with-authority signature
                              of a poisoned/backdoored client; the event
                              names the suspect ids
==================  ========  =============================================

Counter rules are DELTA rules: the watchdog tracks the previous round's
cumulative counters, so a historical anomaly doesn't re-fire forever.
Events append to the pulse stream and (under tracing) become ``health``
trace instants; ``state`` is the worst severity ever seen (sticky), which
is what fedtop's header shows. With ``--health_escalate 1``
:meth:`maybe_escalate` raises :class:`FederationHealthError` on any
critical event — AFTER the round's pulse snapshot is written, so the
stream records what killed the run. Evaluation only reads numbers the
round already computed: a watched run is bit-identical to an unwatched
one.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Optional

_SEVERITY = {"ok": 0, "warn": 1, "critical": 2}
_STATES = {v: k for k, v in _SEVERITY.items()}

#: consecutive strictly-increasing staleness-delta p99 snapshots before the
#: version_lag rule escalates warn -> critical (the monotonic-divergence
#: signature; a noisy-but-bounded lag keeps warning instead)
VERSION_LAG_MONOTONIC_N = 4


class FederationHealthError(RuntimeError):
    """Raised by escalate mode on a critical health event; carries the
    triggering events so the driver can log/act on them."""

    def __init__(self, events: list):
        self.events = list(events)
        rules = ", ".join(sorted({e["rule"] for e in self.events}))
        super().__init__(
            f"federation health critical ({rules}); first event: "
            f"{self.events[0]['detail']}")


class HealthWatchdog:
    """Round-boundary health rules (module docstring)."""

    def __init__(self, *, loss_limit: float = 0.0,
                 stall_sec: Optional[float] = None, stale_spike: int = 8,
                 skew: float = 4.0, version_lag: float = 0.0,
                 update_norm: float = 0.0, drift: float = 0.0,
                 escalate: bool = False,
                 history: int = 256):
        self.loss_limit = float(loss_limit or 0.0)
        self.stall_sec = None if not stall_sec else float(stall_sec)
        self.stale_spike = int(stale_spike or 0)
        self.skew = float(skew or 0.0)
        self.version_lag = float(version_lag or 0.0)
        self.update_norm = float(update_norm or 0.0)
        self.drift = float(drift or 0.0)
        self.escalate = bool(escalate)
        #: last staleness-delta p99 + current monotonic-growth streak
        self._lag_prev: Optional[float] = None
        self._lag_growth = 0
        #: worst severity ever observed (sticky; fedtop's header state)
        self.state = "ok"
        #: bounded event history (a weeks-long run keeps the latest N)
        self.events: deque = deque(maxlen=int(history))
        self._prev_wire: dict = {}
        #: the run-start wire counters (the :meth:`baseline` snapshot) —
        #: kept separately from the rolling ``_prev_wire`` so
        #: :meth:`incident` can report whole-run deltas, not just the
        #: last round's
        self._baseline: dict = {}
        #: delta baseline for the profiles_dropped rule
        self._prev_dropped = 0

    def baseline(self, wire: Optional[dict]) -> None:
        """Seed the delta rules with pre-existing cumulative counters.

        The registry is process-wide: a second federation in one process
        inherits the first one's wire totals, and without a baseline the
        new watchdog would re-fire on round 0 for anomalies that belong to
        a finished run. ``live.configure`` calls this with the registry's
        current wire snapshot."""
        for k, v in (wire or {}).items():
            if isinstance(v, (int, float)):
                self._prev_wire[k] = int(v)
        self._baseline = dict(self._prev_wire)

    def check_round(self, round_idx: int, *, loss: Optional[float] = None,
                    round_ms: Optional[float] = None,
                    wire: Optional[dict] = None,
                    profile: Optional[dict] = None) -> list:
        """Evaluate every rule against one round's signals; returns the
        events that fired (possibly empty). Never raises — escalation is
        the caller's explicit :meth:`maybe_escalate` step, after the
        snapshot carrying these events has been persisted."""
        events: list = []

        def add(rule: str, severity: str, detail: str,
                suspects: Optional[list] = None) -> None:
            ev = {"round": int(round_idx), "rule": rule,
                  "severity": severity, "detail": detail}
            if suspects:
                # only the fedlens attribution rules carry this key, so
                # every pre-lens event dict stays byte-identical
                ev["suspects"] = [int(s) for s in suspects]
            events.append(ev)

        if loss is not None:
            if not math.isfinite(loss):
                add("nan_loss", "critical", f"round loss is {loss!r}")
            elif self.loss_limit > 0.0 and loss > self.loss_limit:
                add("divergent_loss", "critical",
                    f"loss {loss:.6g} exceeds health_loss_limit "
                    f"{self.loss_limit:g}")
        if (self.stall_sec is not None and round_ms is not None
                and round_ms > self.stall_sec * 1e3):
            add("round_stall", "critical",
                f"round took {round_ms:.0f} ms > health_stall_sec "
                f"{self.stall_sec:g}s")
        for key, rule, thresh, severity in (
                ("gave_up", "gave_up", 1, "critical"),
                ("peer_dead", "peer_dead", 1, "warn"),
                ("stale_uploads", "stale_spike", self.stale_spike, "warn")):
            if thresh <= 0:
                continue
            cur = int((wire or {}).get(key, 0) or 0)
            delta = cur - self._prev_wire.get(key, 0)
            self._prev_wire[key] = cur
            if delta >= thresh:
                add(rule, severity, f"{key} +{delta} this round (total {cur})")
        if self.skew > 0.0 and profile:
            # sketch-first: the per-ROUND distribution's p99/p50 (the pulse
            # plane feeds this round's sketch delta here) is the skew
            # signal at cohort scale — a p99 over fewer than ~32 samples
            # is rank noise, so small rounds defer to the EMA p95/p50
            # spread, which also covers pre-sketch profiles
            sk = (profile.get("sketches") or {}).get("train_ms") or {}
            p50, ptail = sk.get("p50"), sk.get("p99")
            basis = "sketch p99/p50 train-ms"
            if not (p50 and ptail) or sk.get("count", 0) < 32:
                ema = profile.get("ema_train_ms") or {}
                p50, ptail = ema.get("p50"), ema.get("p95")
                basis = "p95/p50 EMA train-ms"
            if (p50 and ptail and profile.get("clients_seen", 0) >= 4
                    and ptail / p50 > self.skew):
                add("straggler_skew", "warn",
                    f"{basis} {ptail / p50:.2f} exceeds "
                    f"health_skew {self.skew:g}")
        if self.version_lag > 0.0 and profile:
            # fedbuff divergence watch: THIS round's staleness-sketch delta
            # p99 (versions behind per contribution). Snapshots without the
            # lane (no folds this round) leave the streak untouched — a
            # quiet round is not evidence the lag stopped growing.
            sk = (profile.get("sketches") or {}).get("staleness") or {}
            p99 = sk.get("p99")
            if p99 is not None and sk.get("count", 0) > 0:
                if self._lag_prev is not None and p99 > self._lag_prev:
                    self._lag_growth += 1
                elif self._lag_prev is not None:
                    # equal OR lower resets: the contract is STRICTLY
                    # monotonic growth for N consecutive snapshots — a
                    # plateau (the healthy steady-state lag, and the
                    # common case under ~1% sketch quantization) must not
                    # park an old streak one noise uptick from critical
                    self._lag_growth = 0
                self._lag_prev = float(p99)
                if p99 >= self.version_lag:
                    monotone = self._lag_growth >= VERSION_LAG_MONOTONIC_N
                    add("version_lag",
                        "critical" if monotone else "warn",
                        f"staleness delta p99 {p99:g} versions >= "
                        f"health_version_lag {self.version_lag:g}"
                        + (f"; grew {self._lag_growth} snapshots in a row "
                           "(monotonic divergence)" if monotone else ""))
        # fedlens attribution rules: per-round deltas of the learning
        # lanes (the pulse plane feeds this round's sketch deltas, same as
        # straggler_skew / version_lag) plus the ranked suspects the lens
        # folded for this round — so every event NAMES who to look at
        lens_info = (profile or {}).get("lens") or {}
        suspects = lens_info.get("suspects") or []
        sus_ids = [s.get("client") for s in suspects]
        if self.update_norm > 0.0 and profile:
            sk = (profile.get("sketches") or {}).get("update_norm") or {}
            p99 = sk.get("p99")
            if (p99 is not None and sk.get("count", 0) > 0
                    and p99 >= self.update_norm):
                add("update_norm_spike", "warn",
                    f"update-norm delta p99 {p99:g} >= health_update_norm "
                    f"{self.update_norm:g}", suspects=sus_ids)
        if self.drift > 0.0 and profile:
            sk = (profile.get("sketches") or {}).get("drift") or {}
            p99 = sk.get("p99")
            if (p99 is not None and sk.get("count", 0) > 0
                    and p99 >= self.drift):
                add("client_drift", "warn",
                    f"drift delta p99 {p99:g} >= health_drift "
                    f"{self.drift:g}", suspects=sus_ids)
        if suspects:
            # always armed when the lens surfaces suspects: anti-aligned
            # (cosine <= ANTI_ALIGN) AND norm at/above this round's cohort
            # median (skip the guard when the round carries no norm p50) —
            # an update pushing hard in the opposite direction
            from fedml_tpu.obs.lens import ANTI_ALIGN

            sk = ((profile or {}).get("sketches") or {}).get(
                "update_norm") or {}
            p50 = sk.get("p50")
            bad = [s for s in suspects
                   if s.get("align") is not None
                   and s["align"] <= ANTI_ALIGN
                   and (p50 is None or s.get("norm", 0.0) >= p50)]
            if bad:
                add("aligned_suspects", "critical",
                    f"{len(bad)} anti-aligned high-norm suspect(s) — "
                    "client(s) "
                    + ", ".join(str(int(b["client"])) for b in bad)
                    + f" push against the aggregate (cosine <= {ANTI_ALIGN:g}"
                    " at/above the cohort's median update norm)",
                    suspects=[b["client"] for b in bad])
        if profile:
            cur_dropped = int(profile.get("dropped_ids", 0) or 0)
            delta = cur_dropped - self._prev_dropped
            self._prev_dropped = max(self._prev_dropped, cur_dropped)
            if delta > 0:
                add("profiles_dropped", "warn",
                    f"profiler dropped {delta} client id(s) past max_clients "
                    f"this round (total {cur_dropped}) — per-client telemetry "
                    "is blind to them")
        for ev in events:
            self.events.append(ev)
        worst = max((_SEVERITY[e["severity"]] for e in events),
                    default=_SEVERITY["ok"])
        self.state = _STATES[max(worst, _SEVERITY[self.state])]
        return events

    def incident(self) -> Optional[dict]:
        """Structured view of the watchdog's current incident — the ONE
        API the flight recorder, fedtop and fedpost consume instead of
        re-parsing pulse snapshots: the rule that fired (the most recent
        critical event, falling back to the most recent event of any
        severity), its round and detail, the sticky worst state, the
        whole-run wire-counter deltas vs the :meth:`baseline` snapshot,
        and the recent event tail. None while no rule has ever fired."""
        crit = [e for e in self.events if e["severity"] == "critical"]
        ev = crit[-1] if crit else (self.events[-1] if self.events else None)
        if ev is None:
            return None
        deltas = {}
        for k in sorted(self._prev_wire):
            d = self._prev_wire[k] - self._baseline.get(k, 0)
            if d:
                deltas[k] = d
        return {"rule": ev["rule"], "round": ev["round"],
                "severity": ev["severity"], "detail": ev["detail"],
                "state": self.state, "baseline_deltas": deltas,
                "events": list(self.events)[-16:]}

    def maybe_escalate(self, events: list) -> None:
        """Escalate-to-raise mode: die loudly on this round's critical
        events (no-op when escalation is off or nothing critical fired)."""
        if not self.escalate:
            return
        critical = [e for e in events if e["severity"] == "critical"]
        if critical:
            raise FederationHealthError(critical)
