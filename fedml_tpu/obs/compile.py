"""Compile telemetry: attribute program-build time per round-program shape.

Every distinct round plan (cohort bucket tuple, packed shape key, super-step
block length) compiles its own XLA program, and on the TPU bench host a
fresh compile goes through the remote-compile tunnel — minutes, not
milliseconds. Before this module that cost was invisible: it landed inside
whichever round happened to trigger the build. :func:`timed_build` makes it
first-class:

- a ``compile`` :class:`CounterGroup` on the default registry accumulates
  ``hits`` / ``misses`` / ``build_ms`` / ``first_call_ms`` — cheap enough to
  run unconditionally (each event is one dict store), so the numbers exist
  even in untraced runs (bench.py embeds them in its JSON tail);
- when tracing is on, each build also emits two ``compile``-category spans:
  ``<name>:build`` around the program CONSTRUCTION (builder() returns the
  jitted callable without compiling — usually sub-ms) and
  ``<name>:first_call`` around the first invocation, which is where jax
  traces and XLA compiles before dispatch. With ``async_rounds`` the first
  call still blocks until the executable exists (dispatch needs it), so
  first_call_ms ≈ trace + compile time — the number the tunnel makes
  expensive — without the tracer ever forcing a device sync.

The wrapper returned by :func:`timed_build` is numerically transparent: it
forwards ``*args`` untouched and only reads clocks, preserving the
traced == untraced bit-identity contract.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from fedml_tpu.obs.registry import CounterGroup, default_registry
from fedml_tpu.obs.tracer import tracer_if_enabled

_KEYS = ("hits", "misses", "build_ms", "first_call_ms")
#: module-global strong ref: the registry only holds weakrefs, and compile
#: accounting is process-lifetime (rank 0 owns it so per-rank registry
#: snapshots don't multiply-count one process-wide group)
_GROUP: Optional[CounterGroup] = None


def compile_counters() -> CounterGroup:
    """The process-wide ``compile`` counter group (created on first use)."""
    global _GROUP
    if _GROUP is None:
        _GROUP = default_registry().group("compile", rank=0, keys=_KEYS)
    return _GROUP


def record_cache_hit(name: str) -> None:
    """One LRU hit: the compiled program was reused, no build happened.
    Attributed both in aggregate and per program name, so a report can say
    which cache is hot vs thrashing."""
    g = compile_counters()
    g["hits"] = g.get("hits", 0) + 1
    g[f"hits.{name}"] = g.get(f"hits.{name}", 0) + 1


def timed_build(name: str, shape_key, builder: Callable) -> Callable:
    """Run ``builder()`` under compile telemetry; return the built step
    wrapped so its FIRST invocation (where trace + XLA compile happen) is
    timed and attributed too. ``shape_key`` is recorded (repr'd) on the
    spans so a report can say WHICH program shape cost the time."""
    g = compile_counters()
    tr = tracer_if_enabled(0)
    t0 = time.perf_counter()
    if tr is None:
        fn = builder()
    else:
        with tr.span(f"{name}:build", cat="compile",
                     args={"shape_key": repr(shape_key)}):
            fn = builder()
    # counters bump only once the builder has RETURNED a program: a raising
    # builder propagates with no partial misses/build_ms entry (the caller's
    # LRU never stores the step, so a retry is a fresh build, counted once)
    g["misses"] = g.get("misses", 0) + 1
    g[f"misses.{name}"] = g.get(f"misses.{name}", 0) + 1
    g["build_ms"] = g.get("build_ms", 0.0) + (time.perf_counter() - t0) * 1e3

    first = [True]

    def step(*args):
        if not first[0]:
            return fn(*args)
        tr = tracer_if_enabled(0)
        t0 = time.perf_counter()
        if tr is None:
            out = fn(*args)
        else:
            with tr.span(f"{name}:first_call", cat="compile",
                         args={"shape_key": repr(shape_key)}):
                out = fn(*args)
        # only a SUCCESSFUL first call records first_call_ms: a raise
        # propagates, the flag stays set, and the next invocation is timed
        # as the first (the compile genuinely happens on whichever call
        # completes). The :first_call SPAN above does close on the failed
        # attempt — deliberately: spans record attempts (the time was truly
        # spent), counters record successful compile accounting, so after a
        # retry a trace may carry more first_call spans than the counter.
        first[0] = False
        g["first_call_ms"] = g.get("first_call_ms", 0.0) + (
            time.perf_counter() - t0) * 1e3
        # fedcost static attribution (obs/cost): lower the program we just
        # paid to compile and record its per-op roofline table. Pure
        # tracing — no second compile, no sync — and only when enabled.
        from fedml_tpu.obs import cost as _cost

        if _cost.cost_attribution_enabled():
            _cost.attribute_program(name, shape_key, fn, args)
        return out

    # the packed mesh round carries its un-jitted body as `.raw` (the
    # super-step scans it) and fedpack programs carry fedcost packing
    # hints as `.cost_hints`; keep such sidecar attributes reachable
    for attr in ("raw", "cost_hints"):
        val = getattr(fn, attr, None)
        if val is not None:
            setattr(step, attr, val)
    return step
