"""Per-rank span tracer with cross-rank causality.

One :class:`Tracer` per rank (in-process federations run many ranks in one
process; the per-rank deployment runs one per OS process). Tracer identity
is ``(process_index, rank)``: under ``jax.distributed`` every HOST process
runs the same mesh loop, so each host tags its events with its process
index and flushes to its own file (``trace-p<p>-rank<r>.jsonl``; process 0
keeps the legacy ``trace-rank<r>.jsonl`` name so single-host traces are
unchanged). ``tools/trace_report.py`` merges the per-host files on the
shared wall-µs timebase. Each tracer records
spans (duration events), instants, and counters into a bounded ring buffer
— monotonic-clock durations, wall-clock timestamps for cross-process
alignment — and flushes to ``<trace_dir>/trace-rank<r>.jsonl``.

Causality across ranks: ``comm/message.py:MSG_ARG_KEY_TRACE_CTX``
piggybacks ``(trace_id, parent span id, message uid)`` on every traced
protocol send
(stamped by ``comm/managers._ManagerBase.send_message``, read back on
dispatch), so the analyzer (tools/trace_report.py) links each send span to
the recv span that handled it BY MESSAGE ID, through every transport and
through the reliable/chaos middleware — a retransmit storm collapses onto
the one logical edge it belongs to.

Deterministic head-based sampling (fedsketch): at thousand-client cohorts
the full-fidelity per-round span volume is the plane's scaling wall, so
``--trace_sample_rate r`` keeps only a reproducible fraction of the ROUND
trees. The keep/drop verdict is :func:`span_sampled` — a pure splitmix64
hash of ``(trace seed, round, client/rank id)``, no RNG state, no clocks —
so every rank (and every host, and every re-run) derives the SAME verdict
for a round: a sampled trace is a consistent subset (no rounds missing
ranks), and two runs with the same seed sample the same rounds. Round-level
call sites gate through :func:`tracer_if_sampled`; sampled-out rounds skip
span emission entirely while counters, pulse snapshots and sketch lanes
still see every round — percentiles stay exact while spans stay bounded.

Overhead contract (pinned by tests/test_trace.py):

- disabled (the default): ``tracer_if_enabled(rank)`` is a module-global
  flag check returning ``None`` — call sites skip ALL tracing work,
  allocating nothing;
- enabled: one clock read at span open, one at close, one dict append into
  a bounded ``deque`` (old events fall off; a trace can never exhaust
  memory);
- always: the tracer only reads clocks — a traced run's training outputs
  are bit-identical to an untraced run's.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Optional

def _now_us() -> int:
    # wall-clock µs for CROSS-PROCESS alignment of the per-rank files;
    # durations always come from the monotonic clock below
    return time.time_ns() // 1_000


class _NoopSpan:
    """Singleton returned by a disabled tracer's span() — enter/exit no-ops."""

    __slots__ = ()
    span_id = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, key, value) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("_tr", "name", "cat", "args", "span_id", "parent_id",
                 "_ts_us", "_t0", "_jax_ann")

    def __init__(self, tr: "Tracer", name: str, cat: str, args: Optional[dict],
                 parent_id: Optional[int]):
        self._tr = tr
        self.name = name
        self.cat = cat
        self.args = args
        self.span_id = tr._next_id()
        self.parent_id = parent_id
        self._ts_us = 0
        self._t0 = 0.0
        self._jax_ann = None

    def set(self, key, value) -> None:
        if self.args is None:
            self.args = {}
        self.args[key] = value

    def __enter__(self):
        tr = self._tr
        stack = tr._stack()
        if self.parent_id is None and stack:
            self.parent_id = stack[-1]
        stack.append(self.span_id)
        if tr._jax_bridge is not None:
            self._jax_ann = tr._jax_bridge(f"{self.cat}/{self.name}")
            self._jax_ann.__enter__()
        self._ts_us = _now_us()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur_us = int((time.perf_counter() - self._t0) * 1e6)
        if self._jax_ann is not None:
            self._jax_ann.__exit__(*exc)
        tr = self._tr
        stack = tr._stack()
        if stack and stack[-1] == self.span_id:
            stack.pop()
        tr._emit("X", self.name, self.cat, self._ts_us, dur_us,
                 self.span_id, self.parent_id, self.args)
        return False


class Tracer:
    """Thread-safe per-rank event buffer; see module docstring."""

    def __init__(self, rank: int = 0, buffer_events: int = 65536,
                 trace_id: Optional[str] = None, process: int = 0):
        self.rank = int(rank)
        self.process = int(process)
        self.enabled = True
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        # deque.append is atomic under the GIL; the ring bound makes an
        # unflushed long run degrade to keep-latest instead of OOM
        self._ring: deque = deque(maxlen=int(buffer_events))
        self._ids = iter(range(1, 1 << 62))
        self._id_lock = threading.Lock()
        self._tls = threading.local()
        #: open cross-method spans: key -> (span_id, parent_id, name, cat,
        #: ts_us, t0, args); e.g. the server's round span opens at broadcast
        #: and closes at aggregate, in different handlers
        self._open: dict = {}
        self._open_lock = threading.Lock()
        self._jax_bridge = None
        #: fedflight full-rate retrospective ring (obs/flight.py): when the
        #: flight recorder is armed, every event ALSO lands here — the head
        #: sampler keeps gating what streams, the recorder keeps everything
        #: recent. None (the default) costs one attribute check per emit.
        self._flight_ring = None
        #: lazily-built shadow tracer for sampled-OUT rounds while the
        #: recorder is armed (tracer_if_sampled)
        self._flight_shadow = None

    # -- internals ---------------------------------------------------------
    def _next_id(self) -> int:
        with self._id_lock:
            return next(self._ids)

    def _stack(self) -> list:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def _make_ev(self, ph: str, name: str, cat: str, ts_us: int, dur_us,
                 span_id, parent_id, args) -> dict:
        ev = {"ph": ph, "name": name, "cat": cat, "ts": ts_us,
              "rank": self.rank, "tid": threading.get_ident() & 0xFFFF}
        if self.process:
            # only multi-host events carry the field: single-process traces
            # (and their golden fixtures) keep the exact legacy shape
            ev["proc"] = self.process
        if dur_us is not None:
            ev["dur"] = dur_us
        if span_id:
            ev["sid"] = span_id
        if parent_id:
            ev["psid"] = parent_id
        if args:
            ev["args"] = args
        return ev

    def _emit(self, ph: str, name: str, cat: str, ts_us: int, dur_us,
              span_id, parent_id, args) -> None:
        ev = self._make_ev(ph, name, cat, ts_us, dur_us, span_id,
                           parent_id, args)
        self._ring.append(ev)
        fr = self._flight_ring
        if fr is not None:
            fr.append(ev)

    # -- public API --------------------------------------------------------
    def span(self, name: str, cat: str = "app", args: Optional[dict] = None,
             parent: Optional[int] = None):
        """Context manager tracing a duration event. ``parent`` overrides
        the thread-ambient parent (used to stitch a recv span under the
        sender's context)."""
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self, name, cat, args, parent)

    def begin_span(self, key, name: str, cat: str = "app",
                   args: Optional[dict] = None) -> int:
        """Open a span that a DIFFERENT handler/thread will close (the
        message-driven round spans). Returns the span id."""
        if not self.enabled:
            return 0
        sid = self._next_id()
        stack = self._stack()
        parent = stack[-1] if stack else None
        with self._open_lock:
            self._open[key] = (sid, parent, name, cat, _now_us(),
                               time.perf_counter(), dict(args or {}))
        return sid

    def end_span(self, key, args: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        with self._open_lock:
            rec = self._open.pop(key, None)
        if rec is None:
            return
        sid, parent, name, cat, ts_us, t0, a = rec
        if args:
            a.update(args)
        self._emit("X", name, cat, ts_us,
                   int((time.perf_counter() - t0) * 1e6), sid, parent, a)

    def instant(self, name: str, cat: str = "app",
                args: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        stack = self._stack()
        self._emit("i", name, cat, _now_us(), None, 0,
                   stack[-1] if stack else None, args)

    def counter(self, name: str, values, cat: str = "counter",
                args: Optional[dict] = None) -> None:
        """Counter sample; ``values`` is a number or a {series: number}
        dict (Chrome counter-event semantics)."""
        if not self.enabled:
            return
        v = values if isinstance(values, dict) else {"value": values}
        a = dict(args or {})
        a["values"] = v
        self._emit("C", name, cat, _now_us(), None, 0, None, a)

    def emit_complete(self, name: str, cat: str, ts_us: int, dur_us: int,
                      parent_id: Optional[int] = None,
                      args: Optional[dict] = None) -> int:
        """Emit a complete span with an EXPLICIT placement on the timeline.

        For synthetic attribution spans whose extent was computed, not
        measured around a ``with`` block — e.g. the super-step path amortizes
        one measured device span over its covered rounds by emitting one
        child span per round at ``blk_dur / h`` each. Returns the span id."""
        if not self.enabled:
            return 0
        sid = self._next_id()
        self._emit("X", name, cat, int(ts_us), max(int(dur_us), 0), sid,
                   parent_id, args)
        return sid

    def make_ctx(self, span_id: int) -> list:
        """Wire context for one message: (trace id, parent span id, uid)."""
        return [self.trace_id, int(span_id), uuid.uuid4().hex[:16]]

    def drain(self) -> list[dict]:
        """Atomically take the buffered events (flush consumes them)."""
        out = []
        ring = self._ring
        while True:
            try:
                out.append(ring.popleft())
            except IndexError:
                return out

    def unclosed(self) -> list[dict]:
        """Snapshot of still-open cross-method spans (emitted at flush with
        ph="O" so the analyzer can flag a rank that died mid-round)."""
        with self._open_lock:
            items = list(self._open.items())
        return [{"ph": "O", "name": name, "cat": cat, "ts": ts_us,
                 "rank": self.rank, "sid": sid,
                 **({"proc": self.process} if self.process else {}),
                 **({"psid": parent} if parent else {}),
                 **({"args": a} if a else {})}
                for _k, (sid, parent, name, cat, ts_us, _t0, a) in items]

    def flush(self, path: str, registry=None) -> int:
        """Append drained events (+ a header and a per-rank counter
        snapshot) to ``path`` as JSONL. Returns the event count written."""
        events = self.drain()
        extra = []
        if registry is not None:
            snap = registry.snapshot(rank=self.rank)
            if snap:
                extra.append({"ph": "C", "name": "registry", "cat": "registry",
                              "ts": _now_us(), "rank": self.rank,
                              "args": {"values": snap}})
        extra.extend(self.unclosed())
        if not events and not extra:
            return 0
        header = {"ph": "M", "name": "trace_meta", "rank": self.rank,
                  **({"proc": self.process} if self.process else {}),
                  "ts": _now_us(), "args": {"trace_id": self.trace_id}}
        with open(path, "a") as f:
            for ev in [header, *events, *extra]:
                f.write(json.dumps(ev) + "\n")
        return len(events) + len(extra)


class _FlightShadowTracer(Tracer):
    """Handed out by :func:`tracer_if_sampled` for sampled-OUT rounds while
    the flight recorder is armed: the full public span API, but every
    event lands ONLY in the parent tracer's flight ring — the streamed
    trace keeps the head sampler's reproducible subset while the recorder
    retains everything recent. Span ids come from the parent's counter so
    an incident's merged ring never collides ids with streamed spans of
    neighboring rounds. Cached per parent (``_flight_shadow``), so a
    round's begin_span/end_span pair lands on one ``_open`` table even
    when the two calls re-derive the tracer in different handlers."""

    def __init__(self, parent: Tracer):
        super().__init__(rank=parent.rank, buffer_events=1,
                         trace_id=parent.trace_id, process=parent.process)
        self._parent = parent
        self._jax_bridge = parent._jax_bridge

    def _next_id(self) -> int:
        return self._parent._next_id()

    def _emit(self, ph, name, cat, ts_us, dur_us, span_id, parent_id,
              args) -> None:
        fr = self._parent._flight_ring
        if fr is None:
            return
        fr.append(self._make_ev(ph, name, cat, ts_us, dur_us, span_id,
                                parent_id, args))


class _DisabledTracer(Tracer):
    """Shared no-op tracer handed out while tracing is off; every public
    entry point early-returns on ``enabled`` before touching state."""

    def __init__(self):
        super().__init__(rank=-1, buffer_events=1, trace_id="disabled")
        self.enabled = False


_DISABLED = _DisabledTracer()

# -- process-wide hub ------------------------------------------------------

_lock = threading.Lock()
_ENABLED = False
_TRACE_DIR: Optional[str] = None
_BUFFER = 65536
_TRACERS: dict[int, Tracer] = {}
_TRACE_ID: Optional[str] = None
_JAX_BRIDGE = False
#: head-based span sampling: keep fraction + the seed the pure verdict
#: hashes (defaults = keep everything, the pre-fedsketch behavior)
_SAMPLE_RATE = 1.0
_SAMPLE_SEED = 0
#: fedflight hook (obs/flight.py): ``recorder.ring_for`` while the flight
#: recorder is armed — get_tracer attaches the per-(process, rank) flight
#: ring at tracer creation; None (the default) keeps the hot path at one
#: attribute check per emit
_FLIGHT_RING_FACTORY = None


def set_flight_ring_factory(factory) -> None:
    """Install (or, with None, remove) the flight-ring factory and
    re-attach/detach the ring on every LIVE tracer — called by
    ``obs.flight.configure`` so a recorder armed mid-process still
    captures ranks that started tracing earlier."""
    global _FLIGHT_RING_FACTORY
    with _lock:
        _FLIGHT_RING_FACTORY = factory
        for tr in _TRACERS.values():
            tr._flight_ring = (None if factory is None
                               else factory(tr.rank, tr.process))

_M64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    """One splitmix64 mixing step — the standard 64-bit finalizer; full
    avalanche, so adjacent (seed, round, id) triples decorrelate."""
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return (x ^ (x >> 31)) & _M64


def span_sampled(round_idx: int, entity: int = 0, *,
                 rate: Optional[float] = None,
                 seed: Optional[int] = None) -> bool:
    """The head-based keep/drop verdict: a pure function of
    ``(trace seed, round, entity)`` — deterministic across ranks, hosts,
    threads and re-runs; no state is consulted or advanced.

    ``entity`` defaults to 0 so every rank of a federation derives ONE
    shared verdict per round (a sampled trace never has rounds missing
    ranks); pass a client/rank id for finer per-entity span families (the
    FedBuff per-client spans to come)."""
    r = _SAMPLE_RATE if rate is None else float(rate)
    if r >= 1.0:
        return True
    if r <= 0.0:
        return False
    s = _SAMPLE_SEED if seed is None else int(seed)
    h = _splitmix64(s & _M64)
    h = _splitmix64(h ^ (int(round_idx) & _M64))
    h = _splitmix64(h ^ (int(entity) & _M64))
    # top 53 bits -> uniform [0, 1): exact on every platform's float64
    return (h >> 11) * (2.0 ** -53) < r
#: this host's process index under jax.distributed; None = resolve lazily
#: from jax.process_index() at first tracer creation
_PROCESS: Optional[int] = None


def set_process_index(process_index: Optional[int]) -> None:
    """Pin this process's tracer identity (the ``p`` of (process, rank)).

    ``parallel/mesh.init_multihost`` calls this with ``jax.process_index()``
    after joining the cluster; ``None`` restores lazy resolution. Existing
    tracers are NOT retagged — set it before the run starts tracing."""
    global _PROCESS
    with _lock:
        _PROCESS = None if process_index is None else int(process_index)


def _process_index() -> int:
    """Resolved process index (0 outside multi-process runs). Never forces
    backend init: an unpinned index only asks jax when a distributed client
    is already up, so single-process tracing stays jax-init-free."""
    if _PROCESS is not None:
        return _PROCESS
    try:
        import jax

        if jax.distributed.is_initialized():
            return jax.process_index()
    except Exception:  # pragma: no cover - jax always importable here
        pass
    return 0


def configure(trace_dir: Optional[str], buffer_events: int = 65536,
              jax_bridge: bool = False, trace_id: Optional[str] = None,
              sample_rate: float = 1.0, sample_seed: int = 0) -> None:
    """Enable tracing into ``trace_dir`` (None disables). Existing
    per-rank tracers are kept so an in-flight run reconfiguring is safe.
    ``sample_rate``/``sample_seed`` drive :func:`span_sampled`'s
    deterministic head-based round sampling (1.0 = keep every round)."""
    global _ENABLED, _TRACE_DIR, _BUFFER, _TRACE_ID, _JAX_BRIDGE
    global _SAMPLE_RATE, _SAMPLE_SEED
    if not 0.0 <= sample_rate <= 1.0:
        raise ValueError(
            f"sample_rate must be in [0, 1], got {sample_rate}")
    with _lock:
        _TRACE_DIR = trace_dir
        _ENABLED = bool(trace_dir)
        _BUFFER = max(int(buffer_events), 1)
        _JAX_BRIDGE = bool(jax_bridge)
        _TRACE_ID = trace_id or uuid.uuid4().hex[:16]
        _SAMPLE_RATE = float(sample_rate)
        _SAMPLE_SEED = int(sample_seed)
        if _ENABLED:
            os.makedirs(trace_dir, exist_ok=True)


_NO_TRACE_DIR = object()


def configure_from(config) -> bool:
    """Configure from a FedConfig-shaped object; returns whether tracing is
    now enabled. The one call every entry point (train()/run loops) makes —
    the config's ``trace_dir`` is authoritative, so a run with it unset
    DISABLES tracing left on by an earlier run in the same process (its
    events would otherwise append into the previous run's trace files).
    Only a config without the attribute at all leaves tracing untouched."""
    # fedcost, fedpulse and fedflight ride the same entry-point hook: a
    # config carrying cost_attribution / pulse_path / flight_dir configures
    # static roofline attribution, the live telemetry plane and the flight
    # recorder here too
    from fedml_tpu.obs import cost as _cost
    from fedml_tpu.obs import flight as _flight
    from fedml_tpu.obs import live as _live

    _cost.configure_from(config)
    _live.configure_from(config)
    _flight.configure_from(config)
    trace_dir = getattr(config, "trace_dir", _NO_TRACE_DIR)
    if trace_dir is _NO_TRACE_DIR:
        return tracing_enabled()
    if not trace_dir:
        if tracing_enabled():
            configure(None)
        return False
    configure(trace_dir,
              buffer_events=getattr(config, "trace_buffer_events", 65536),
              jax_bridge=bool(getattr(config, "profile_dir", None)),
              # the run seed doubles as the trace seed: re-running the same
              # config samples the same rounds (BlazeFL-grade replays)
              sample_rate=getattr(config, "trace_sample_rate", 1.0),
              sample_seed=getattr(config, "seed", 0))
    return True


def tracing_enabled() -> bool:
    return _ENABLED


def get_tracer(rank: int = 0) -> Tracer:
    """The rank's tracer (created on first use), or the shared disabled
    tracer while tracing is off."""
    if not _ENABLED:
        return _DISABLED
    rank = int(rank)
    with _lock:
        tr = _TRACERS.get(rank)
        if tr is None:
            tr = _TRACERS[rank] = Tracer(rank, buffer_events=_BUFFER,
                                         trace_id=_TRACE_ID,
                                         process=_process_index())
            if _FLIGHT_RING_FACTORY is not None:
                tr._flight_ring = _FLIGHT_RING_FACTORY(tr.rank, tr.process)
            if _JAX_BRIDGE:
                try:
                    import jax

                    tr._jax_bridge = jax.profiler.TraceAnnotation
                except Exception:  # pragma: no cover - jax always present here
                    tr._jax_bridge = None
        return tr


def tracer_if_enabled(rank: int = 0) -> Optional[Tracer]:
    """Hot-path gate: ``None`` while tracing is off — one global read, no
    allocation — else the rank's tracer."""
    if not _ENABLED:
        return None
    return get_tracer(rank)


def tracer_if_sampled(rank: int = 0, round_idx: int = 0) -> Optional[Tracer]:
    """Round-level hot-path gate: ``None`` while tracing is off (one global
    read, nothing allocated — same contract as :func:`tracer_if_enabled`)
    OR while this round is head-sampled out; else the rank's tracer. The
    per-round span call sites (round/mesh_step/prefetch/edge train) gate
    through this so a ``--trace_sample_rate`` run emits a bounded,
    reproducible span subset."""
    if not _ENABLED:
        return None
    if _SAMPLE_RATE < 1.0 and not span_sampled(round_idx):
        # fedflight retroactive capture: while the recorder is armed the
        # sampled-out round still emits — through a shadow tracer whose
        # events land ONLY in the flight ring, never in the stream
        # benign racy read of the arm gate: the factory is installed at
        # configure time before federations start; the worst a torn read
        # costs is one sampled-out round missing from a recorder armed
        # mid-run, never a wrong event  # fedlint: disable=check-then-act
        if _FLIGHT_RING_FACTORY is None:
            return None
        tr = get_tracer(rank)
        if tr._flight_ring is None:
            return None
        shadow = tr._flight_shadow
        if shadow is None:
            shadow = tr._flight_shadow = _FlightShadowTracer(tr)
        return shadow
    return get_tracer(rank)


def trace_filename(rank: int, process: int = 0) -> str:
    """Per-(process, rank) trace file name. Process 0 keeps the legacy
    single-host name so existing traces and tooling are unchanged; other
    hosts get a distinct file they can write into a SHARED directory
    without clobbering each other."""
    if process:
        return f"trace-p{process}-rank{rank}.jsonl"
    return f"trace-rank{rank}.jsonl"


def flush_all(trace_dir: Optional[str] = None) -> list[str]:
    """Flush every live tracer to its per-(process, rank) file (append),
    including a per-rank counter snapshot from the default registry.
    Returns the paths written."""
    from fedml_tpu.obs.registry import default_registry

    d = trace_dir or _TRACE_DIR
    if not d:
        return []
    os.makedirs(d, exist_ok=True)
    with _lock:
        tracers = list(_TRACERS.values())
    paths = []
    for tr in tracers:
        p = os.path.join(d, trace_filename(tr.rank, tr.process))
        if tr.flush(p, registry=default_registry()):
            paths.append(p)
    return paths


def reset() -> None:
    """Drop all tracers and disable tracing (tests; never mid-run). Also
    tears down the fedpulse plane — a plane leaked across tests would feed
    every later run_round in the process — and the packed-schedule
    fallback accounting (warn-once set + "packed" registry counter lane),
    so a second federation in one process warns and counts afresh instead
    of inheriting the first's suppression."""
    global _ENABLED, _TRACE_DIR, _TRACE_ID, _PROCESS
    global _SAMPLE_RATE, _SAMPLE_SEED
    with _lock:
        _ENABLED = False
        _TRACE_DIR = None
        _TRACE_ID = None
        _PROCESS = None
        _SAMPLE_RATE = 1.0
        _SAMPLE_SEED = 0
        _TRACERS.clear()
    from fedml_tpu.obs import flight as _flight
    from fedml_tpu.obs import lens as _lens
    from fedml_tpu.obs import live as _live

    _live.reset()
    _flight.reset()
    _lens.reset()
    import sys

    packed = sys.modules.get("fedml_tpu.parallel.packed")
    if packed is not None:   # only if already imported — never import here
        packed.reset_fallback_warnings()
