"""fedflight: anomaly-triggered flight recorder + incident bundles.

The observability plane DETECTS trouble (the HealthWatchdog escalates,
the gateway quarantines, the reliable layer declares peers dead) but
until now detection ended in a raised :class:`FederationHealthError`
with only the *sampled* trace stream on disk — and under
``--trace_sample_rate`` the rounds that caused the incident are usually
the rounds the sampler dropped. This module is the black-box recorder:
always-on bounded retrospective buffers plus a triggered dump.

While armed (``--flight_dir``), the recorder retains the last
``--flight_window`` rounds of:

- **full-rate round spans** — a second, per-rank ring beside the
  tracer's event ring (``Tracer._flight_ring``). The PR-10 head sampler
  keeps gating what *streams* to the trace files; the flight ring
  receives EVERY event, including those of sampled-out rounds (which
  emit through a shadow tracer that writes only here). Ring bound:
  ``flight_window * EVENTS_PER_ROUND`` events per rank, so a weeks-long
  run degrades to keep-latest instead of OOM.
- **pulse snapshots** — the per-round dicts the pulse plane assembles
  (registry counter lanes, per-round sketch deltas via ``Sketch.since``,
  profiler aggregates, the watchdog verdict), ring-keyed per scope
  (tenant or the default federation) so a gateway tenant's incident
  never interleaves another tenant's rounds.
- **watchdog state transitions** — each snapshot carries
  ``health.state``; the bundle's ``watchdog.json`` is the structured
  :meth:`~fedml_tpu.obs.health.HealthWatchdog.incident` view (rule,
  round, counter deltas vs the run baseline).

Triggers (armed by the ``--flight_on`` comma list):

==============  ============================================================
``escalate``    watchdog escalation — the pulse plane records the round and
                triggers *before* ``maybe_escalate`` raises (live.py), so
                the bundle exists when FederationHealthError propagates
``quarantine``  gateway lane escalation/crash — tenant-scoped bundle via
                the lane's pinned plane (``PulsePlane.tenant``)
``peer_dead``   reliable-layer first-death of a peer (retry budget
                exhausted; comm/reliable.py's off-lock gave-up hook)
``manual``      ``obs.flight.trigger()`` or SIGUSR2
==============  ============================================================

The incident id is PURE in ``(seed, round, rule)`` — the same splitmix64
chain the head sampler uses — so every rank (and every host, and the
re-run) derives the SAME ``incident-<id>`` name with no coordination:
cross-rank capture rides a fire-and-forget ``MSG_TYPE_FLIGHT_DUMP``
control broadcast (the edge servers send it before re-raising; each send
is individually try/excepted and nothing waits for acks, so a dead peer
bounds the flush at the transport's send deadline instead of hanging
teardown), and per-process ranks dump into the same bundle directory by
name alone. Dumps are idempotent per (incident, rank).

Bundle layout (``incident-<id>/``)::

    manifest.json       id, rule, round, trigger kind, tenant, seed,
                        chaos_seed, env versions, the sanitized config,
                        the EXACT replay command, file inventory
                        (written LAST, atomically — its presence is the
                        completeness marker tools/fedpost.py keys on)
    ring-rank<r>.jsonl  per-rank full-rate flight-ring dump
    trace-merged.jsonl  all rings merged on the wall-µs timebase
    rounds.jsonl        windowed round records + per-round lane deltas
                        (+ the fedlens ``learning`` lane — suspects and
                        all — when ``--lens on`` armed the run)
    pulse-tail.jsonl    the raw recent pulse snapshots (fedtop shape)
    watchdog.json       the structured watchdog.incident() view
    cost.json/plan.json fedcost tables / fedplan decisions, when present

Contracts (the tracer's discipline, restated):

- off by default and **allocation-free when off**: call sites gate
  through :func:`recorder_if_enabled` (one module-global read returning
  ``None``) and the tracer's hot path sees one ``_flight_ring is None``
  attribute check (pinned by tests/test_flight.py's tracemalloc test);
- **bit-identity**: the recorder only reads what the round already
  produced — snapshots, events, clocks — so a recorder-on run computes
  exactly the recorder-off weights;
- overhead rides the PR-10 ≤5% full-plane budget (re-pinned with the
  recorder on at the 10k-cohort recipe).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Optional

from fedml_tpu.obs import tracer as _tracer

__all__ = [
    "DEFAULT_TRIGGERS", "EVENTS_PER_ROUND", "FlightRecorder", "configure",
    "configure_from", "flight_enabled", "handle_dump_message", "incident_id",
    "last_incident", "recorder_if_enabled", "replay_command", "reset",
    "session_stats", "trigger",
]

#: trigger inventory (the --flight_on vocabulary)
DEFAULT_TRIGGERS = ("escalate", "quarantine", "peer_dead", "manual")

#: flight-ring sizing: events retained per rank = window * this. A
#: round-scale span tree is the round span + per-message send/recv pairs
#: + pipeline stages + health/counter instants; the busiest edge rounds
#: in the tree emit O(10) events per worker per round, so 512 covers a
#: 32-worker federation's round ~1.5x over. Deliberately generous —
#: at ~200 B/event the window-8 default holds 4096 events ≈ 0.8 MB/rank.
EVENTS_PER_ROUND = 512

#: process-lifetime stats for the conftest session summary (NEVER reset —
#: they describe the session, not one run; a green tier-1 run expects 0)
_SESSION = {"incidents": 0, "last_bundle": None}

_M64 = (1 << 64) - 1


def incident_id(seed: int, round_idx: int, rule: str) -> str:
    """Deterministic incident id: the head sampler's splitmix64 chain over
    ``(seed, round, rule)``. Pure — no clocks, no RNG state — so every
    rank, host and replay derives the same 16-hex id for one incident and
    per-process dumps converge on one bundle directory by name alone."""
    rule_key = int.from_bytes(
        rule.encode("utf-8", "replace")[:8].ljust(8, b"\0"), "little")
    h = _tracer._splitmix64(int(seed) & _M64)
    h = _tracer._splitmix64(h ^ (int(round_idx) & _M64))
    h = _tracer._splitmix64(h ^ rule_key)
    return f"{h:016x}"


def _jsonable(v) -> bool:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return True
    if isinstance(v, (list, tuple)):
        return all(_jsonable(x) for x in v)
    if isinstance(v, dict):
        return all(isinstance(k, str) and _jsonable(x) for k, x in v.items())
    return False


def replay_command(config: dict, *, seed: int = 0, chaos_seed: int = 0,
                   algorithm: Optional[str] = None) -> str:
    """The exact command reproducing the incident run: the unified launcher
    plus every flag whose value differs from the FedConfig default, with
    the determinism keys (seed, chaos_seed) always pinned. Purity of the
    run in (seed, chaos_seed, flags) — the BlazeFL replay argument — is
    what turns the bundle into a *reproducible* incident."""
    from fedml_tpu.core.config import FedConfig

    base = FedConfig().to_dict()
    parts = ["python", "-m", "fedml_tpu.experiments.run"]
    if algorithm:
        parts += ["--algorithm", str(algorithm)]
    for k in sorted(config or {}):
        if k not in base or k in ("seed", "chaos_seed"):
            continue
        v = config[k]
        if v == base[k] or v is None or not _jsonable(v):
            continue
        if isinstance(v, bool):
            v = int(v)
        parts += [f"--{k}", str(v)]
    parts += ["--seed", str(int(seed)), "--chaos_seed", str(int(chaos_seed))]
    return " ".join(parts)


class FlightRecorder:
    """Bounded retrospective buffers + the triggered bundle dump."""

    def __init__(self, flight_dir: str, *, window: int = 8,
                 triggers=DEFAULT_TRIGGERS, seed: int = 0,
                 chaos_seed: int = 0, config_dict: Optional[dict] = None,
                 algorithm: Optional[str] = None):
        self.flight_dir = os.path.abspath(flight_dir)
        self.window = max(int(window), 1)
        self.triggers = frozenset(
            t.strip() for t in (triggers.split(",")
                                if isinstance(triggers, str) else triggers)
            if t and t.strip())
        self.seed = int(seed)
        self.chaos_seed = int(chaos_seed)
        self.config = dict(config_dict or {})
        self.algorithm = algorithm
        self._lock = threading.Lock()
        #: scope ("default" or a tenant id) -> deque of recent pulse snaps
        self._rounds: dict = {}
        #: (process, rank) -> the full-rate flight event ring handed to
        #: that rank's tracer (tracer._emit appends; we only ever read)
        self._rings: dict = {}
        #: incident id -> bundle path (idempotence within this process)
        self._done: dict = {}
        self._last: Optional[dict] = None
        os.makedirs(self.flight_dir, exist_ok=True)

    # -- capture (the always-on cheap half) --------------------------------

    def ring_for(self, rank: int, process: int = 0) -> deque:
        """The (process, rank) flight ring, created on first use — the
        tracer attaches this beside its own event ring."""
        key = (int(process), int(rank))
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = deque(
                    maxlen=self.window * EVENTS_PER_ROUND)
            return ring

    def record_round(self, snap: dict, *, watchdog=None,
                     tenant: Optional[str] = None,
                     events: Optional[list] = None) -> None:
        """Round-boundary feed from the pulse plane: retain the snapshot in
        the scope's window ring, then — when the round's events carry a
        critical and the watchdog would escalate — trigger the dump HERE,
        before ``maybe_escalate`` raises (the dump-before-raise ordering
        the acceptance contract pins)."""
        scope = tenant if tenant is not None else "default"
        with self._lock:
            ring = self._rounds.get(scope)
            if ring is None:
                ring = self._rounds[scope] = deque(maxlen=self.window)
            ring.append(snap)
        if not events or watchdog is None or not watchdog.escalate:
            return
        critical = [e for e in events if e["severity"] == "critical"]
        if not critical:
            return
        kind = "quarantine" if tenant is not None else "escalate"
        self.trigger(critical[0]["rule"], snap.get("round", 0), kind=kind,
                     reason=critical[0]["detail"], tenant=tenant,
                     watchdog=watchdog)

    # -- the trigger -------------------------------------------------------

    def trigger(self, rule: str, round_idx: int, *, kind: str = "manual",
                reason: str = "", tenant: Optional[str] = None,
                watchdog=None, incident: Optional[str] = None
                ) -> Optional[str]:
        """Dump an incident bundle; returns its path (or None when the
        trigger ``kind`` is not armed by --flight_on). Idempotent: a
        second trigger resolving to the same incident id returns the
        existing bundle. ``incident`` overrides the derived id — the
        cross-rank dump handler passes the broadcast id verbatim so a
        worker whose config drifted can never fork the bundle."""
        if incident is None and kind not in self.triggers:
            return None
        iid = incident or incident_id(self.seed, int(round_idx), rule)
        with self._lock:
            done = self._done.get(iid)
        if done is not None:
            return done
        path = self._dump(iid, rule, int(round_idx), kind=kind,
                          reason=reason, tenant=tenant, watchdog=watchdog)
        with self._lock:
            self._done[iid] = path
            self._last = {"id": iid, "rule": rule, "round": int(round_idx),
                          "kind": kind, "tenant": tenant, "bundle": path}
        _SESSION["incidents"] += 1
        _SESSION["last_bundle"] = path
        return path

    def last_incident(self) -> Optional[dict]:
        with self._lock:
            return dict(self._last) if self._last else None

    # -- the dump ----------------------------------------------------------

    def _dump(self, iid: str, rule: str, round_idx: int, *, kind: str,
              reason: str, tenant: Optional[str], watchdog) -> str:
        ddir = os.path.join(self.flight_dir, f"incident-{iid}")
        os.makedirs(ddir, exist_ok=True)

        with self._lock:
            rings = {k: list(r) for k, r in self._rings.items()}
            scope = tenant if tenant is not None else "default"
            snaps = list(self._rounds.get(scope, ()))

        merged = []
        for (process, rank), events in sorted(rings.items()):
            name = (f"ring-p{process}-rank{rank}.jsonl" if process
                    else f"ring-rank{rank}.jsonl")
            self._write_jsonl(os.path.join(ddir, name), events)
            merged.extend(events)
        merged.sort(key=lambda ev: ev.get("ts", 0))
        self._write_jsonl(os.path.join(ddir, "trace-merged.jsonl"), merged)

        self._write_jsonl(os.path.join(ddir, "pulse-tail.jsonl"), snaps)
        self._write_jsonl(os.path.join(ddir, "rounds.jsonl"),
                          self._round_records(snaps))

        wd = None
        if watchdog is not None:
            try:
                wd = watchdog.incident()
            except Exception:
                wd = None
        self._write_json(os.path.join(ddir, "watchdog.json"),
                         wd or {"rule": rule, "round": round_idx,
                                "detail": reason})

        # fedcost / fedplan context, when those planes ran this process
        try:
            from fedml_tpu.obs import cost as _cost

            tables = _cost.cost_tables()
            if tables:
                safe = {k: v for k, v in tables.items() if _jsonable(v)}
                if safe:
                    self._write_json(os.path.join(ddir, "cost.json"), safe)
        except Exception:
            pass
        try:
            from fedml_tpu.obs import plan as _plan

            st = _plan.cache_stats()
            if st.get("hits") or st.get("misses"):
                self._write_json(os.path.join(ddir, "plan.json"), st)
        except Exception:
            pass

        # manifest LAST (atomic replace): its presence marks the bundle
        # complete — fedpost exits 1 on a directory that lacks it
        manifest = {
            "v": 1, "id": iid, "rule": rule, "round": round_idx,
            "kind": kind, "reason": reason, "tenant": tenant,
            "ts_ms": int(time.time() * 1e3),
            "seed": self.seed, "chaos_seed": self.chaos_seed,
            "window": self.window,
            "env": self._env_versions(),
            # self.config is the plain flag DICT captured at configure
            # time, not a FedConfig — .items() is dict iteration, not a
            # flag read  # fedlint: disable=config-flag-drift
            "config": {k: v for k, v in self.config.items()
                       if _jsonable(v)},
            "replay_cmd": replay_command(
                self.config, seed=self.seed, chaos_seed=self.chaos_seed,
                algorithm=self.algorithm),
        }
        manifest["files"] = sorted(
            set(os.listdir(ddir)) | {"manifest.json"})
        self._write_json(os.path.join(ddir, "manifest.json"), manifest)
        return ddir

    def _round_records(self, snaps: list) -> list:
        """Compact windowed round records with per-round counter-lane
        deltas (cumulative lane minus the previous retained round's — the
        registry-snapshot-delta view fedpost's verdict reads)."""
        out = []
        prev_lanes: dict = {}
        for snap in snaps:
            lanes = snap.get("lanes") or {}
            deltas: dict = {}
            for ns, counters in lanes.items():
                prev = prev_lanes.get(ns) or {}
                d = {}
                for k, v in counters.items():
                    if not isinstance(v, (int, float)) or isinstance(v, bool):
                        continue
                    dv = v - prev.get(k, 0)
                    if dv:
                        d[k] = round(dv, 3) if isinstance(dv, float) else dv
                if d:
                    deltas[ns] = d
            prev_lanes = lanes
            health = snap.get("health") or {}
            rec = {
                "round": snap.get("round"), "ts_ms": snap.get("ts_ms"),
                "source": snap.get("source"), "loss": snap.get("loss"),
                "round_ms": snap.get("round_ms"),
                "cohort": snap.get("cohort"),
                "lane_deltas": deltas,
                "state": health.get("state"),
                "events": health.get("events") or [],
            }
            # fedlens lane: keep the per-round suspect attribution in the
            # compact records too, so fedpost's suspects section works from
            # rounds.jsonl alone (pulse-tail.jsonl carries the full snaps)
            learning = snap.get("learning")
            if learning is not None:
                rec["learning"] = learning
            out.append(rec)
        return out

    @staticmethod
    def _env_versions() -> dict:
        env = {"python": sys.version.split()[0]}
        for mod in ("jax", "jaxlib", "numpy"):
            try:
                env[mod] = __import__(mod).__version__
            except Exception:
                env[mod] = None
        return env

    @staticmethod
    def _write_jsonl(path: str, rows: list) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for row in rows:
                f.write(json.dumps(row, default=float) + "\n")
        os.replace(tmp, path)

    @staticmethod
    def _write_json(path: str, obj) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1, sort_keys=True, default=float)
            f.write("\n")
        os.replace(tmp, path)


# -- process-wide hub --------------------------------------------------------

_RECORDER: Optional[FlightRecorder] = None
_SIGUSR2_INSTALLED = False


def recorder_if_enabled() -> Optional[FlightRecorder]:
    """Hot-path gate: ``None`` while the recorder is off — one module
    global read, no allocation — else the process recorder."""
    return _RECORDER


def flight_enabled() -> bool:
    return _RECORDER is not None


def configure(flight_dir: Optional[str], *, window: int = 8,
              triggers=DEFAULT_TRIGGERS, seed: int = 0, chaos_seed: int = 0,
              config_dict: Optional[dict] = None,
              algorithm: Optional[str] = None) -> Optional[FlightRecorder]:
    """(Re)build the process recorder (``configure(None)`` disarms it) and
    attach/detach the full-rate flight rings on every live tracer plus all
    tracers created later. Returns the recorder (or None)."""
    global _RECORDER
    if not flight_dir:
        _RECORDER = None
        _tracer.set_flight_ring_factory(None)
        return None
    rec = FlightRecorder(flight_dir, window=window, triggers=triggers,
                         seed=seed, chaos_seed=chaos_seed,
                         config_dict=config_dict, algorithm=algorithm)
    _RECORDER = rec
    _tracer.set_flight_ring_factory(rec.ring_for)
    if "manual" in rec.triggers:
        _install_sigusr2()
    return rec


_NO_FLIGHT = object()


def configure_from(config) -> bool:
    """Configure from a FedConfig-shaped object (chained from
    ``tracer.configure_from`` so every entry point makes the one call).
    Same semantics as the tracer/pulse planes: ``flight_dir`` is
    authoritative — unset DISARMS a recorder left on by an earlier run in
    the process; only a config without the attribute leaves it alone."""
    fdir = getattr(config, "flight_dir", _NO_FLIGHT)
    if fdir is _NO_FLIGHT:
        return flight_enabled()
    if not fdir:
        if flight_enabled():
            configure(None)
        return False
    cfg_dict: dict = {}
    to_dict = getattr(config, "to_dict", None)
    if callable(to_dict):
        try:
            cfg_dict = {k: v for k, v in to_dict().items() if _jsonable(v)}
        except Exception:
            cfg_dict = {}
    configure(fdir,
              window=getattr(config, "flight_window", 8),
              triggers=getattr(config, "flight_on",
                               ",".join(DEFAULT_TRIGGERS)),
              seed=getattr(config, "seed", 0),
              chaos_seed=getattr(config, "chaos_seed", 0),
              config_dict=cfg_dict)
    return True


def trigger(rule: str = "manual", round_idx: int = 0, *,
            kind: str = "manual", reason: str = "",
            tenant: Optional[str] = None) -> Optional[str]:
    """Manual trigger: dump a bundle now (None when the recorder is off or
    the kind is not armed). The SIGUSR2 handler routes here."""
    rec = _RECORDER
    if rec is None:
        return None
    return rec.trigger(rule, round_idx, kind=kind, reason=reason,
                       tenant=tenant)


def last_incident() -> Optional[dict]:
    """The most recent incident's {id, rule, round, kind, tenant, bundle}
    — what the edge servers broadcast as MSG_TYPE_FLIGHT_DUMP args."""
    rec = _RECORDER
    return rec.last_incident() if rec is not None else None


def handle_dump_message(msg_params: dict, rank: int = 0) -> Optional[str]:
    """Receiver side of the MSG_TYPE_FLIGHT_DUMP broadcast: flush this
    process's rings into the broadcast incident id's bundle. Idempotent —
    in-process federations share one recorder that already dumped every
    rank, so the handler resolves to the existing bundle; a per-process
    gRPC rank writes its own ring files into the same directory name."""
    from fedml_tpu.comm.message import (
        MSG_ARG_KEY_FLIGHT_ID,
        MSG_ARG_KEY_FLIGHT_ROUND,
        MSG_ARG_KEY_FLIGHT_RULE,
    )

    rec = _RECORDER
    if rec is None:
        return None
    iid = msg_params.get(MSG_ARG_KEY_FLIGHT_ID)
    if not iid:
        return None
    return rec.trigger(str(msg_params.get(MSG_ARG_KEY_FLIGHT_RULE, "remote")),
                       int(msg_params.get(MSG_ARG_KEY_FLIGHT_ROUND, 0) or 0),
                       kind="remote", reason=f"flight_dump received on "
                       f"rank {rank}", incident=str(iid))


def _install_sigusr2() -> None:
    """Best-effort SIGUSR2 -> manual trigger (main thread only; platforms
    without the signal, or handler installation from a worker thread,
    silently skip — the in-process trigger() path always works)."""
    global _SIGUSR2_INSTALLED
    if _SIGUSR2_INSTALLED:
        return
    try:
        import signal

        def _on_sigusr2(signum, frame):  # pragma: no cover - signal path
            trigger("sigusr2", 0, kind="manual", reason="SIGUSR2")

        signal.signal(signal.SIGUSR2, _on_sigusr2)
        _SIGUSR2_INSTALLED = True
    except Exception:
        pass


def reset() -> None:
    """Disarm and drop the recorder (tests; never mid-run). Chained from
    ``tracer.reset()``. Session stats survive — they describe the
    process, not one run."""
    configure(None)


def session_stats() -> dict:
    """Process-lifetime flight stats (the conftest ``[t1] incidents:``
    session line)."""
    return dict(_SESSION)
