"""Device-memory sampler: an HBM lane in the trace without a profiler run.

The ROADMAP gap this closes: device-side visibility used to require a
separate ``--profile_dir`` run through the jax profiler. This sampler
instead snapshots ``jax.local_devices()`` ``memory_stats()`` (bytes_in_use
and the peak watermark) at ROUND BOUNDARIES and emits them as ``device``-
category counter events, which the Perfetto export renders as a dedicated
"devices" counter lane next to the span timeline.

Overhead contract (the sampler's side of DESIGN.md §12):

- only runs when tracing is enabled — the untraced hot path never reaches
  this module;
- one ``memory_stats()`` call per local device per round, host-side only:
  it reads allocator counters, never syncs or touches the device stream;
- backends without allocator stats (CPU returns None) fall back to ONE
  host RSS read (``/proc/self/statm``) so the lane exists everywhere the
  tests run; the keys name their source (``d<i>/...`` vs ``host/...``).
"""

from __future__ import annotations

import os
from typing import Optional

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _host_rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return None


def sample_device_memory(tr, round_idx: Optional[int] = None) -> dict:
    """Snapshot per-device memory onto ``tr`` as a ``device_mem`` counter.

    Returns the sampled values (tests read them directly). ``tr`` must be
    an ENABLED tracer — call sites gate on ``tracer_if_enabled``."""
    import jax

    vals: dict = {}
    for d in jax.local_devices():
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        vals[f"d{d.id}/bytes_in_use"] = int(ms.get("bytes_in_use", 0))
        peak = ms.get("peak_bytes_in_use")
        if peak is not None:
            vals[f"d{d.id}/peak_bytes"] = int(peak)
    if not vals:
        rss = _host_rss_bytes()
        if rss is not None:
            vals["host/rss_bytes"] = rss
    if vals:
        tr.counter("device_mem", vals, cat="device",
                   args=None if round_idx is None else {"round": round_idx})
    return vals
