"""Per-client profile store: the fedpulse memory of who trained how fast.

Every remaining ROADMAP item keys on *per-client* signals the post-hoc
trace stack cannot serve live: heterogeneity-aware cohort scheduling wants
observed client speed (FedML Parrot, arXiv:2303.01778), FedBuff-style
buffered aggregation wants per-client staleness, and the participation-
fairness question ("which clients never get sampled?") needs counts at the
342k-client cross-device scale. :class:`ClientProfiler` is that store:

- **array-backed, bounded**: one flat numpy array per field, indexed by
  logical client id — no per-client Python objects, no dicts. 28 bytes per
  client slot (EMA train-ms f32, cumulative upload bytes f64, participation
  i32, last-seen round i32, fedlens EMA update-norm + drift f32), grown
  geometrically to the highest observed id
  and hard-capped at ``max_clients`` (ids beyond the cap are counted in
  ``dropped``, never silently indexed). 342,477 clients ≈ 10 MB; the store
  can never balloon past ``max_clients * 28`` bytes, and ``nbytes`` reports
  the measured footprint so tests pin the bound instead of trusting it.
- **paradigm-agnostic feed**: the simulation paradigms feed it from the
  traced ``FedAvgAPI.run_round`` wrapper (cohort ids from the round plan,
  round wall amortized per client — clients train fused under one vmap, so
  per-client wall does not exist there); the edge server feeds it per
  upload on the broadcast→aggregate path (arrival latency + payload bytes,
  attributed to the worker's assigned logical clients — the same observed-
  speed signal the straggler deadline acts on).
- **query surface for the consumers to come**: :meth:`speed_rank` (cohort
  scheduling), :meth:`staleness` (FedBuff weighting),
  :meth:`participation_fairness` (sampling audits), and :meth:`aggregates`
  (the compact round-boundary summary the pulse stream and fedtop render).
- **sketch lanes (fedsketch)**: alongside the per-client EMAs, six
  process-cumulative :class:`~fedml_tpu.obs.sketch.Sketch` lanes record
  the *distributions* the means hide — ``train_ms`` (per-client walls),
  ``upload_ms`` (broadcast→upload latency per contribution),
  ``payload_bytes`` (per contribution), ``staleness`` (rounds-behind
  per contribution; the sync paths feed it from the stale-upload drop
  path, and the fedbuff async server writes every fold's version lag —
  the signal the watchdog's ``version_lag`` rule reads), and the fedlens
  learning lanes ``update_norm`` / ``drift`` (per-client update L2 and
  1 - cosine-vs-aggregate per contribution; their PER-ROUND deltas feed
  the ``update_norm_spike`` / ``client_drift`` watchdog rules). Fixed-
  memory and mergeable across hosts; their measured bytes count into
  :attr:`nbytes` so the store's bound stays honest.

Thread-safe (the edge server's handler thread and the sim loop may share
one process-wide profiler); EMA uses a fixed ``ema_alpha`` so a client's
speed estimate tracks drift without unbounded history.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from fedml_tpu.obs.sketch import Sketch

#: bytes per client slot across the six field arrays
#: (f32 + f64 + 2*i32 + 2*f32 fedlens EMAs)
BYTES_PER_CLIENT = 28

#: the profiler's distribution lanes, in pulse-snapshot render order (the
#: last two are the fedlens learning lanes — obs/lens.LENS_LANES)
SKETCH_LANES = ("train_ms", "upload_ms", "payload_bytes", "staleness",
                "update_norm", "drift")


def _gini(values: np.ndarray) -> float:
    """Gini coefficient of a non-negative sample (0 = perfectly even
    participation, -> 1 = one client absorbs everything)."""
    x = np.sort(np.asarray(values, np.float64))
    n = x.size
    total = float(x.sum())
    if n == 0 or total <= 0.0:
        return 0.0
    i = np.arange(1, n + 1, dtype=np.float64)
    return float(((2.0 * i - n - 1.0) * x).sum() / (n * total))


class ClientProfiler:
    """Bounded array-backed per-client profile store (module docstring)."""

    def __init__(self, capacity_hint: int = 1024,
                 max_clients: int = 2_097_152, ema_alpha: float = 0.2,
                 sketch_alpha: float = 0.01):
        if max_clients < 1:
            raise ValueError(f"max_clients must be >= 1, got {max_clients}")
        if not 0.0 < ema_alpha <= 1.0:
            raise ValueError(f"ema_alpha must be in (0, 1], got {ema_alpha}")
        self.max_clients = int(max_clients)
        self.ema_alpha = float(ema_alpha)
        self.sketch_alpha = float(sketch_alpha)
        self._cap = min(max(int(capacity_hint), 16), self.max_clients)
        self._lock = threading.Lock()
        self._alloc(self._cap)
        #: highest observed id + 1 (the live prefix of the arrays)
        self._n = 0
        #: ids rejected by the max_clients bound (surfaced, never indexed)
        self.dropped = 0
        #: highest round index ever observed (staleness base)
        self.last_round = -1
        #: fedsketch distribution lanes (module docstring); cumulative over
        #: the run, one shared universe so per-host sketches merge exactly
        self.sketches: dict = {lane: Sketch(alpha=self.sketch_alpha)
                               for lane in SKETCH_LANES}

    def _alloc(self, cap: int) -> None:
        self._ema_train_ms = np.zeros(cap, np.float32)
        self._upload_bytes = np.zeros(cap, np.float64)
        self._participation = np.zeros(cap, np.int32)
        self._last_seen = np.full(cap, -1, np.int32)
        # fedlens learning EMAs (0 until a lens-armed round observes the id)
        self._lens_norm = np.zeros(cap, np.float32)
        self._lens_drift = np.zeros(cap, np.float32)

    def _ensure(self, n: int) -> None:
        if n <= self._cap:
            return
        cap = self._cap
        while cap < n:
            cap *= 2
        cap = min(cap, self.max_clients)
        for name in ("_ema_train_ms", "_upload_bytes", "_participation",
                     "_last_seen", "_lens_norm", "_lens_drift"):
            old = getattr(self, name)
            new = (np.full(cap, -1, old.dtype) if name == "_last_seen"
                   else np.zeros(cap, old.dtype))
            new[: old.size] = old
            setattr(self, name, new)
        self._cap = cap

    def reset(self) -> None:
        """Zero every profile (bench phase boundaries); capacity is kept."""
        with self._lock:
            self._alloc(self._cap)
            self._n = 0
            self.dropped = 0
            self.last_round = -1
            self.sketches = {lane: Sketch(alpha=self.sketch_alpha)
                             for lane in SKETCH_LANES}

    # -- feed ---------------------------------------------------------------

    def observe(self, client_ids, round_idx: int, *, train_ms=None,
                upload_bytes=None) -> None:
        """Record one participation event for each id in ``client_ids``.

        ``train_ms`` / ``upload_bytes`` are scalars (shared by the batch —
        the sim paradigm's amortized round wall) or per-id arrays (the edge
        server's per-upload attribution). A client's FIRST observation seeds
        its EMA directly; later ones blend with ``ema_alpha``. Ids must be
        unique within one call (cohorts are)."""
        ids = np.atleast_1d(np.asarray(client_ids, np.int64))
        if ids.size == 0:
            return
        with self._lock:
            bad = (ids < 0) | (ids >= self.max_clients)
            if bad.any():
                self.dropped += int(bad.sum())
                keep = ~bad
                ids = ids[keep]
                if train_ms is not None and np.ndim(train_ms):
                    train_ms = np.asarray(train_ms)[keep]
                if upload_bytes is not None and np.ndim(upload_bytes):
                    upload_bytes = np.asarray(upload_bytes)[keep]
                if ids.size == 0:
                    return
            self._ensure(int(ids.max()) + 1)
            self._n = max(self._n, int(ids.max()) + 1)
            first = self._participation[ids] == 0
            self._participation[ids] += 1
            self._last_seen[ids] = int(round_idx)
            self.last_round = max(self.last_round, int(round_idx))
            if train_ms is not None:
                t = np.asarray(train_ms, np.float32)
                a = self.ema_alpha
                prev = self._ema_train_ms[ids]
                self._ema_train_ms[ids] = np.where(
                    first, t, (1.0 - a) * prev + a * t)
                # sketch lane: one sample per participating client (the
                # amortized sim feed repeats one scalar cohort-wide — the
                # count= form skips materializing the copies)
                if np.ndim(t):
                    self.sketches["train_ms"].add(t)
                else:
                    self.sketches["train_ms"].add(t, count=int(ids.size))
            if upload_bytes is not None:
                self._upload_bytes[ids] += np.asarray(upload_bytes, np.float64)

    def observe_lens(self, client_ids, round_idx: int, *, update_norm=None,
                     drift=None) -> None:
        """fedlens per-client learning-signal feed: per-id update L2 norms
        and drift (1 - cosine vs the round aggregate), from a lens-armed
        round (sim stash or edge per-upload stats). Seeds/blends the
        per-client EMAs exactly like :meth:`observe` and adds every sample
        to the ``update_norm`` / ``drift`` sketch lanes (whose per-round
        deltas the watchdog's attribution rules read). Does NOT count as a
        participation event — the round wrapper already recorded one."""
        ids = np.atleast_1d(np.asarray(client_ids, np.int64))
        if ids.size == 0:
            return
        with self._lock:
            bad = (ids < 0) | (ids >= self.max_clients)
            if bad.any():
                self.dropped += int(bad.sum())
                keep = ~bad
                ids = ids[keep]
                if update_norm is not None and np.ndim(update_norm):
                    update_norm = np.asarray(update_norm)[keep]
                if drift is not None and np.ndim(drift):
                    drift = np.asarray(drift)[keep]
                if ids.size == 0:
                    return
            self._ensure(int(ids.max()) + 1)
            self._n = max(self._n, int(ids.max()) + 1)
            self.last_round = max(self.last_round, int(round_idx))
            a = self.ema_alpha
            if update_norm is not None:
                v = np.asarray(update_norm, np.float32)
                first = self._lens_norm[ids] == 0.0
                prev = self._lens_norm[ids]
                self._lens_norm[ids] = np.where(
                    first, v, (1.0 - a) * prev + a * v)
                if np.ndim(v):
                    self.sketches["update_norm"].add(v)
                else:
                    self.sketches["update_norm"].add(v, count=int(ids.size))
            if drift is not None:
                v = np.asarray(drift, np.float32)
                first = self._lens_drift[ids] == 0.0
                prev = self._lens_drift[ids]
                self._lens_drift[ids] = np.where(
                    first, v, (1.0 - a) * prev + a * v)
                if np.ndim(v):
                    self.sketches["drift"].add(v)
                else:
                    self.sketches["drift"].add(v, count=int(ids.size))

    def observe_wire(self, *, upload_ms=None, payload_bytes=None,
                     staleness=None) -> None:
        """Per-CONTRIBUTION sketch feed (no client attribution): the edge
        server records each upload's broadcast→upload latency and decoded
        payload bytes once per upload (not once per assigned logical
        client), and every contribution's rounds-behind — 0 for an on-time
        upload, the deadline-closed lag for a stale one, the fold's
        version lag for a fedbuff contribution (same lane, one merged
        distribution)."""
        with self._lock:
            if upload_ms is not None:
                self.sketches["upload_ms"].add(upload_ms)
            if payload_bytes is not None:
                self.sketches["payload_bytes"].add(payload_bytes)
            if staleness is not None:
                self.sketches["staleness"].add(staleness)

    # -- queries ------------------------------------------------------------

    @property
    def nbytes(self) -> int:
        """Measured store footprint (the bound the tests pin) — the flat
        per-client arrays PLUS the sketch lanes' sparse stores (each
        structurally capped at its bucket-universe size). Locked: `observe`
        on the handler thread swaps the arrays when `_ensure` doubles
        capacity, and half-grown reads would double-count."""
        with self._lock:
            return self._nbytes_locked()

    def _nbytes_locked(self) -> int:
        # callers hold self._lock (aggregates() sums this inside its
        # snapshot section; taking the plain Lock again would deadlock)
        return int(self._ema_train_ms.nbytes + self._upload_bytes.nbytes
                   + self._participation.nbytes + self._last_seen.nbytes
                   + self._lens_norm.nbytes + self._lens_drift.nbytes
                   + sum(sk.nbytes for sk in self.sketches.values()))

    def sketch_summaries(self) -> dict:
        """Non-empty sketch lanes as compact summaries (count + p50/p90/p99)
        in lane order — the pulse snapshot / bench-tail block. Locked: a
        feed thread mutating a lane mid-iteration would otherwise race the
        quantile walk."""
        with self._lock:
            return {lane: self.sketches[lane].summary()
                    for lane in SKETCH_LANES if self.sketches[lane].n}

    def sketch_copies(self) -> dict:
        """One locked pass returning copies of the non-empty lanes, so the
        pulse plane can derive summaries, encodings AND per-round deltas
        without re-taking the lock per view."""
        with self._lock:
            return {lane: self.sketches[lane].copy()
                    for lane in SKETCH_LANES if self.sketches[lane].n}

    def snapshot(self):
        """Immutable schedule-time view for the fedsched cohort scheduler
        (data/sched.ProfileSnapshot): seen ids ascending + their EMA
        train-ms and participation counts, copied under the lock. Ids the
        cap dropped are — by construction — absent, so a scheduler holding
        this snapshot treats them as unseen cold-starts, never an index
        error."""
        from fedml_tpu.data.sched import ProfileSnapshot

        with self._lock:
            ids = self._seen_ids()
            return ProfileSnapshot(
                ids=ids.astype(np.int64),
                ema_train_ms=self._ema_train_ms[ids].copy(),
                participation=self._participation[ids].copy())

    @property
    def clients_seen(self) -> int:
        # locked: _ensure's growth swaps _participation for a larger array
        # while observe holds the lock; pairing the stale array with the
        # new _n would scan garbage tail entries
        with self._lock:
            return int((self._participation[: self._n] > 0).sum())

    def _seen_ids(self) -> np.ndarray:
        return np.nonzero(self._participation[: self._n] > 0)[0]

    def speed_rank(self, k: Optional[int] = None,
                   slowest_first: bool = True) -> np.ndarray:
        """Seen client ids ordered by EMA train-ms — the observed-speed
        ranking a heterogeneity-aware cohort scheduler consumes. Ties keep
        id order (stable sort) so the ranking is deterministic."""
        with self._lock:
            ids = self._seen_ids()
            ema = self._ema_train_ms[ids]
        order = np.argsort(-ema if slowest_first else ema, kind="stable")
        out = ids[order]
        return out if k is None else out[: int(k)]

    def staleness(self, round_idx: Optional[int] = None) -> np.ndarray:
        """``[ids, rounds_since_last_seen]`` (2 x n_seen) — the FedBuff
        staleness signal, relative to ``round_idx`` (default: the newest
        observed round)."""
        with self._lock:
            ids = self._seen_ids()
            last = self._last_seen[ids]
            # capture under the lock: observe() bumps last_round on the
            # handler thread, and a post-release read could pair a newer
            # base with the older ids/last snapshot (negative staleness)
            newest = self.last_round
        base = newest if round_idx is None else int(round_idx)
        return np.stack([ids, base - last.astype(np.int64)])

    def participation_fairness(self) -> dict:
        """Participation-count spread over the SEEN clients: a sampling
        audit (gini 0 = every seen client trained equally often)."""
        with self._lock:
            part = self._participation[: self._n]
            part = part[part > 0]
        if part.size == 0:
            return {"clients_seen": 0, "gini": 0.0, "min": 0, "max": 0,
                    "mean": 0.0}
        return {"clients_seen": int(part.size),
                "gini": round(_gini(part), 4),
                "min": int(part.min()), "max": int(part.max()),
                "mean": round(float(part.mean()), 3)}

    def aggregates(self, round_idx: Optional[int] = None,
                   top_k: int = 5, include_sketches: bool = True) -> dict:
        """Compact round-boundary summary for the pulse stream / fedtop:
        counts, participation fairness, EMA train-ms distribution, the
        ``top_k`` slowest clients, staleness spread, store footprint, and
        (by default) the cumulative sketch summaries — the bench-tail
        block. The pulse plane passes ``include_sketches=False``: it
        derives both cumulative and per-round views from its own
        ``sketch_copies()`` pass, so computing them here too would walk
        every lane's quantiles twice per round."""
        with self._lock:
            n = self._n
            part = self._participation[:n]
            seen = part > 0
            ns = int(seen.sum())
            out = {"clients_seen": ns, "store_bytes": self._nbytes_locked(),
                   "dropped_ids": int(self.dropped)}
            if ns == 0:
                return out
            ids = np.nonzero(seen)[0]
            ema = self._ema_train_ms[ids]
            last = self._last_seen[ids]
            upload = float(self._upload_bytes[:n].sum())
            pseen = part[ids]
            newest = self.last_round
        out["participation"] = {
            "mean": round(float(pseen.mean()), 3), "max": int(pseen.max()),
            "gini": round(_gini(pseen), 4)}
        if upload > 0:
            out["upload_mb"] = round(upload / 1e6, 3)
        if float(ema.max(initial=0.0)) > 0.0:
            out["ema_train_ms"] = {
                "mean": round(float(ema.mean()), 3),
                "p50": round(float(np.percentile(ema, 50)), 3),
                "p95": round(float(np.percentile(ema, 95)), 3)}
            order = np.argsort(-ema, kind="stable")[: int(top_k)]
            out["stragglers"] = [
                {"client": int(ids[j]), "ema_ms": round(float(ema[j]), 3),
                 "rounds": int(pseen[j])} for j in order]
        # `newest` was captured inside the lock with ids/last: a fresher
        # last_round paired with the older snapshot would skew staleness
        base = newest if round_idx is None else int(round_idx)
        st = base - last.astype(np.int64)
        out["staleness"] = {"mean": round(float(st.mean()), 3),
                            "max": int(st.max())}
        if include_sketches:
            sketches = self.sketch_summaries()
            if sketches:
                out["sketches"] = sketches
        return out
