"""Unified metrics registry: one store behind every counter surface.

Before this module the tree had four disjoint metric surfaces with no
shared schema: ``RoundTimer.sums`` (per-phase dicts), the reliable layer's
``stats`` dict, the chaos layer's ``stats`` dict, and the pipeline's
``_stage_rows``. Each keeps its exact public shape — dict-style reads and
writes, same key names — but the dicts are now :class:`CounterGroup` views
attached to the process-wide :class:`MetricsRegistry`, so one snapshot call
answers "what did the wire/timing/pipeline counters across every live
manager in this process add up to" without knowing who owns which dict.

Design constraints inherited from the surfaces being unified:

- writes stay lock-free on the hot path (the wire counters are bumped from
  retransmit threads and were already documented as monotonic ints read
  without locks — a CounterGroup write is one dict store, exactly as
  before);
- attaching a group never extends its owner's lifetime: the registry holds
  weak references, a GC'd RoundTimer drops out of snapshots on its own;
- groups are PER-OWNER (each manager, timer, pipeline keeps its own view,
  so tests and concurrent runs stay isolated) while ``snapshot`` sums
  across owners — the registry-level view is additive by construction,
  mirroring ``merge_wire_stats``.
"""

from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Iterator, Optional


class CounterGroup:
    """Dict-like counter view registered under a namespace.

    Supports the exact access patterns of the dicts it replaces:
    ``g["k"] += 1``, ``g.get("k", 0)``, ``g.items()``, ``"k" in g``,
    ``dict(g)``. Values are plain numbers; writes are single dict stores
    (no lock — the owners treat these as monotonic summary counters).
    """

    __slots__ = ("_data", "namespace", "rank", "__weakref__")

    def __init__(self, namespace: str, rank: Optional[int] = None, keys=()):
        self.namespace = namespace
        self.rank = rank
        self._data: dict = {k: 0 for k in keys}

    def __getitem__(self, key):
        return self._data[key]

    def __setitem__(self, key, value) -> None:
        self._data[key] = value

    def get(self, key, default=None):
        return self._data.get(key, default)

    def items(self):
        return self._data.items()

    def keys(self):
        return self._data.keys()

    def values(self):
        return self._data.values()

    def __iter__(self) -> Iterator:
        return iter(self._data)

    def __contains__(self, key) -> bool:
        return key in self._data

    def __len__(self) -> int:
        return len(self._data)

    def __eq__(self, other) -> bool:
        if isinstance(other, CounterGroup):
            return self._data == other._data
        return self._data == other

    def __repr__(self) -> str:
        return f"CounterGroup({self.namespace!r}, rank={self.rank}, {self._data!r})"

    def update(self, other) -> None:
        self._data.update(other)

    def as_dict(self) -> dict:
        return dict(self._data)


class MetricsRegistry:
    """Weak-ref'd collection of :class:`CounterGroup`\\ s by namespace."""

    def __init__(self):
        self._lock = threading.Lock()
        self._groups: dict[str, list] = {}          # namespace -> [weakref]
        self._rows: dict[str, list[dict]] = {}      # namespace -> row records

    def group(self, namespace: str, rank: Optional[int] = None,
              keys=()) -> CounterGroup:
        """Create and attach a new counter group under ``namespace``."""
        g = CounterGroup(namespace, rank=rank, keys=keys)
        with self._lock:
            refs = self._groups.setdefault(namespace, [])
            refs.append(weakref.ref(g))
            # opportunistic purge of dead owners, keeps the list bounded
            self._groups[namespace] = [r for r in refs if r() is not None]
        return g

    def _live(self, namespace: str) -> list[CounterGroup]:
        with self._lock:
            refs = list(self._groups.get(namespace, ()))
        return [g for g in (r() for r in refs) if g is not None]

    def namespaces(self) -> list[str]:
        with self._lock:
            return sorted(set(self._groups) | set(self._rows))

    def snapshot(self, namespace: Optional[str] = None,
                 rank: Optional[int] = None) -> dict:
        """Sum counters across live groups. ``namespace=None`` walks every
        namespace, prefixing keys ``<namespace>/<key>`` (the wandb-style
        flat keying of utils/metrics.wire_stats). ``rank`` filters to
        groups owned by that rank."""
        if namespace is None:
            out: dict = {}
            for ns in self.namespaces():
                for k, v in self.snapshot(ns, rank=rank).items():
                    out[f"{ns}/{k}"] = v
            return out
        total: dict = {}
        for g in self._live(namespace):
            if rank is not None and g.rank is not None and g.rank != rank:
                continue
            for k, v in g.items():
                total[k] = total.get(k, 0) + v
        return total

    # -- row records (per-round stage timings, utils/metrics.round_stats) --
    def append_row(self, namespace: str, row: dict,
                   cap: int = 4096) -> None:
        with self._lock:
            rows = self._rows.setdefault(namespace, [])
            rows.append(dict(row))
            if len(rows) > cap:
                del rows[: len(rows) - cap]

    def rows(self, namespace: str) -> list[dict]:
        with self._lock:
            return list(self._rows.get(namespace, ()))

    def clear_rows(self, namespace: Optional[str] = None) -> None:
        with self._lock:
            if namespace is None:
                self._rows.clear()
            else:
                self._rows.pop(namespace, None)


_DEFAULT = MetricsRegistry()

#: per-thread registry override (registry_scope). The gateway runs each
#: tenant's handler lane on its own thread under a scope, so every counter
#: surface the lane touches (reliable wire groups, the server's stale lane,
#: pulse snapshots) attaches to THAT tenant's registry — cross-tenant
#: counter isolation without threading a registry through every call site.
_TLS = threading.local()


def default_registry() -> MetricsRegistry:
    """The registry the calling thread's counter surfaces attach to: the
    thread's :func:`registry_scope` override when one is active, else the
    process-wide default. The common (scope-less) path is two attribute
    reads and no allocation."""
    reg = getattr(_TLS, "registry", None)
    return reg if reg is not None else _DEFAULT


@contextlib.contextmanager
def registry_scope(registry: MetricsRegistry):
    """Route this THREAD's ``default_registry()`` calls to ``registry`` for
    the duration of the block (re-entrant: the previous override — if any —
    is restored on exit). Other threads are unaffected."""
    prev = getattr(_TLS, "registry", None)
    _TLS.registry = registry
    try:
        yield registry
    finally:
        _TLS.registry = prev
