"""Unified experiment launcher (fed_launch counterpart).

``python -m fedml_tpu.experiments.run --algorithm fedavg --dataset mnist
--model lr --comm_round 20`` — flags mirror the reference mains
(main_fedavg.py:48-120) via the FedConfig argparse bridge.
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Optional, Sequence

from fedml_tpu.core.config import add_args, config_from_args
from fedml_tpu.experiments import ALGORITHMS, run_experiment


def main(argv: Optional[Sequence[str]] = None, default_algorithm: str = "fedavg") -> dict:
    parser = add_args()
    parser.add_argument("--algorithm", type=str, default=default_algorithm,
                        choices=sorted(ALGORITHMS))
    parser.add_argument("--result_json", type=str, default=None,
                        help="write the FULL result dict (history lists "
                             "included) to this path")
    ns = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(filename)s[line:%(lineno)d] %(levelname)s %(message)s",
    )
    algorithm = ns.algorithm
    result_json = ns.result_json
    del ns.algorithm, ns.result_json
    cfg = config_from_args(ns)
    result = run_experiment(cfg, algorithm)
    if result_json:
        with open(result_json, "w") as f:
            json.dump({"algorithm": algorithm, **dict(result)}, f)
    printable = {}
    for k, v in dict(result).items():
        if isinstance(v, list) and v and isinstance(v[-1], (int, float)):
            printable[k] = v[-1]          # history series -> final value
        elif isinstance(v, (int, float, str)):
            printable[k] = v
    print(json.dumps({"algorithm": algorithm, **printable}))
    return result


if __name__ == "__main__":
    main(sys.argv[1:])
