"""Unified experiment launcher (fed_launch counterpart).

``python -m fedml_tpu.experiments.run --algorithm fedavg --dataset mnist
--model lr --comm_round 20`` — flags mirror the reference mains
(main_fedavg.py:48-120) via the FedConfig argparse bridge.
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Optional, Sequence

from fedml_tpu.core.config import add_args, config_from_args
from fedml_tpu.experiments import ALGORITHMS, run_experiment


def main(argv: Optional[Sequence[str]] = None, default_algorithm: str = "fedavg") -> dict:
    parser = add_args()
    parser.add_argument("--algorithm", type=str, default=default_algorithm,
                        choices=sorted(ALGORITHMS))
    ns = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(filename)s[line:%(lineno)d] %(levelname)s %(message)s",
    )
    algorithm = ns.algorithm
    del ns.algorithm
    cfg = config_from_args(ns)
    result = run_experiment(cfg, algorithm)
    printable = {}
    for k, v in dict(result).items():
        if isinstance(v, list) and v and isinstance(v[-1], (int, float)):
            printable[k] = v[-1]          # history series -> final value
        elif isinstance(v, (int, float, str)):
            printable[k] = v
    print(json.dumps({"algorithm": algorithm, **printable}))
    return result


if __name__ == "__main__":
    main(sys.argv[1:])
