"""Thin alias of the unified launcher (reference fedml_experiments pattern:
one main per algorithm). Equivalent to --algorithm fedavg_edge — the
message-driven FedAvg deployment (reference mpirun + FedAvgAPI.py:20-28
rank branch), over the in-process router or gRPC with --backend grpc."""

import sys

from fedml_tpu.experiments.run import main

if __name__ == "__main__":
    main(sys.argv[1:], default_algorithm="fedavg_edge")
