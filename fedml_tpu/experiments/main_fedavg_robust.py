"""Thin alias of the unified launcher (reference fedml_experiments pattern:
one main per algorithm). Equivalent to --algorithm fedavg_robust."""

import sys

from fedml_tpu.experiments.run import main

if __name__ == "__main__":
    main(sys.argv[1:], default_algorithm="fedavg_robust")
