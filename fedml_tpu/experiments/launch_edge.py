"""Spawn a multi-process edge federation on this host.

Counterpart of the reference's mpirun wrapper
(fedml_experiments/distributed/fedavg/run_fedavg_distributed_pytorch.sh:21-23:
``mpirun -np $PROCESS_NUM -hostfile ./mpi_host_file python3 main_fedavg.py``):
one OS process per rank, rank 0 = server. Each child is

    python -m fedml_tpu.experiments.main_fedavg_edge \
        --rank R --world_size N [--grpc_ipconfig_path ...] <passthrough flags>

so the exact same per-rank entry deploys across machines — run it by hand
(or via your scheduler) on each host with a shared grpc_ipconfig csv
(reference grpc_ipconfig.csv, grpc_comm_manager.py:59-60). This helper just
automates the single-host case. See docs/deploy.md for the runbook.

All FedConfig flags pass through to every rank — including the wire
reliability/chaos knobs (--wire_reliable, --chaos_seed, --chaos_drop,
--chaos_dup, --chaos_delay_ms, --chaos_reorder, --chaos_crash_rank,
--chaos_crash_after; docs/deploy.md "Wire reliability"), so a lossy-wire
rehearsal runs with the exact deployment entry points.

Usage:
    python -m fedml_tpu.experiments.launch_edge --world_size 3 \
        --dataset synthetic_1_1 --model lr --comm_round 5 [flags...]
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Optional, Sequence


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--world_size" not in argv:
        print("launch_edge: --world_size N is required", file=sys.stderr)
        return 2
    n = int(argv[argv.index("--world_size") + 1])
    if any(a == "--rank" for a in argv):
        print("launch_edge: do not pass --rank; it is assigned per process",
              file=sys.stderr)
        return 2
    # --result_json names ONE output file: only the server's history goes
    # there, so route the flag to rank 0 alone
    result_json = []
    if "--result_json" in argv:
        i = argv.index("--result_json")
        result_json = argv[i:i + 2]
        del argv[i:i + 2]

    procs = []
    try:
        for rank in range(n):
            cmd = [sys.executable, "-m", "fedml_tpu.experiments.main_fedavg_edge",
                   "--rank", str(rank), *argv,
                   *(result_json if rank == 0 else [])]
            # rank 0 (server) inherits stdout so its result JSON reaches the
            # caller; workers log to stderr only
            procs.append(subprocess.Popen(
                cmd,
                stdout=None if rank == 0 else subprocess.DEVNULL,
                env=os.environ.copy(),
            ))
        rcs = [p.wait() for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    bad = [(r, rc) for r, rc in enumerate(rcs) if rc != 0]
    if bad:
        print(f"launch_edge: ranks failed: {bad}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
