"""Thin alias of the unified launcher (reference fedml_experiments pattern:
one main per algorithm). Equivalent to --algorithm fedagc."""

import sys

from fedml_tpu.experiments.run import main

if __name__ == "__main__":
    main(sys.argv[1:], default_algorithm="fedagc")
