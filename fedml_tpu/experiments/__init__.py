"""Experiment entry points (L5).

Counterpart of reference fedml_experiments/: per-algorithm argparse mains
(standalone/distributed/centralized trees) plus the unified ``fed_launch``
launcher (fedml_experiments/distributed/fed_launch/main.py:52-68). Here one
dispatcher serves every algorithm; the per-algorithm ``main_*`` modules are
thin aliases, so ``python -m fedml_tpu.experiments.main_fedavg --dataset
mnist --model lr`` mirrors the reference's invocation shape 1:1 while
``python -m fedml_tpu.experiments.run --algorithm X`` is the fed_launch
form. The --ci fast path shrinks rounds/epochs like the reference CI
scripts (CI-script-fedavg.sh:34-38).
"""

from __future__ import annotations

import json
import logging

from fedml_tpu.core.config import FedConfig

log = logging.getLogger(__name__)

ALGORITHMS = (
    "fedavg", "crosssilo_fedavg", "fedopt", "fedprox", "fednova", "fedagc",
    "fedavg_robust", "hierarchical", "decentralized", "turboaggregate",
    "fedgkt", "fednas", "fedseg", "splitnn", "vfl", "centralized",
    "silo_fedavg", "silo_fedopt", "silo_fednova", "silo_fedagc",
    "crosssilo_fedopt", "crosssilo_fednova", "crosssilo_fedagc",
    "crosssilo_fedavg_robust", "crosssilo_fedprox", "crosssilo_decentralized",
    "crosssilo_fedseg", "crosssilo_hierarchical", "crosssilo_fednas",
    "streaming_fedavg", "fedavg_edge",
)


def _bundle_for(config: FedConfig, ds):
    from fedml_tpu.models import create_model

    return create_model(
        config.model, ds.class_num,
        input_shape=ds.train_x.shape[2:] or None,
    )


def _load(config: FedConfig):
    from fedml_tpu.data import load_dataset

    # loader parameter names vary (client_num_in_total vs num_clients);
    # every loader ignores unknown kwargs, so pass both spellings
    return load_dataset(
        config.dataset,
        data_dir=config.data_dir,
        client_num_in_total=config.client_num_in_total,
        num_clients=config.client_num_in_total,
        partition_method=config.partition_method,
        partition_alpha=config.partition_alpha,
        batch_size=config.batch_size,
        seed=config.seed,
    )


def run_experiment(config: FedConfig, algorithm: str) -> dict:
    """Build data + model + API for `algorithm`, run it, return its final
    history/metrics dict (also JSON-logged, wandb-style keys). On
    successful completion, signals any sweep orchestrator listening on
    FEDML_SWEEP_PIPE (reference fedavg/utils.py:19-26 posts the same from
    the server manager at end of run) — exactly once per experiment."""
    result = _run_experiment(config, algorithm)
    from fedml_tpu.utils.metrics import notify_sweep_complete

    notify_sweep_complete()
    return result


def _run_experiment(config: FedConfig, algorithm: str) -> dict:
    algorithm = algorithm.lower()
    if algorithm not in ALGORITHMS:
        raise KeyError(f"unknown algorithm {algorithm!r}; known: {ALGORITHMS}")
    if config.rank is not None and algorithm != "fedavg_edge":
        # silently running the full single-process simulation on N machines
        # would be N-fold redundant work and no federation at all
        raise ValueError(
            "--rank/--world_size start one process of a multi-process "
            "deployment, which only the fedavg_edge algorithm supports "
            f"(got --algorithm {algorithm})"
        )

    if algorithm == "vfl":
        from fedml_tpu.algorithms.vfl import VFLAPI
        from fedml_tpu.data.vertical import (
            load_lending_club, load_nus_wide, load_uci_credit,
            make_synthetic_vertical,
        )

        loaders = {
            "lending_club": load_lending_club,
            "nus_wide": load_nus_wide,
            "uci_credit": load_uci_credit,
        }
        vds = loaders.get(
            config.dataset,
            lambda d, seed=0, **_: make_synthetic_vertical(seed=seed),
        )(config.data_dir, seed=config.seed)
        api = VFLAPI(vds, lr=config.lr, batch_size=config.batch_size, seed=config.seed)
        result = api.fit(epochs=config.comm_round, seed=config.seed)
        log.info("result %s", json.dumps(result))
        return result

    ds = _load(config)

    if algorithm == "fedavg_edge":
        # the message-driven deployment (reference mpirun path): 1 server +
        # N workers over the in-process router, or real gRPC loopback with
        # --backend grpc — with optional payload compression (--wire_codec)
        # and error-feedback delta uploads (--wire_delta)
        from fedml_tpu.distributed.fedavg_edge import run_fedavg_edge

        if config.rank is not None:
            # TRUE multi-process deployment: this process is ONE rank of a
            # gRPC federation (reference: mpirun starts N processes, each
            # branching on its rank — FedAvgAPI.py:20-28). Start it with
            # experiments.launch_edge or by hand on each machine.
            from fedml_tpu.distributed.fedavg_edge import run_fedavg_edge_rank

            agg = run_fedavg_edge_rank(ds, config)
            if agg is None:       # worker rank: nothing to report
                return {"rank": config.rank, "role": "worker"}
            hist = agg.test_history
            return {"rank": 0, "role": "server",
                    "round": [h["round"] for h in hist],
                    "Test/Acc": [h["acc"] for h in hist],
                    "Test/Loss": [h["loss"] for h in hist]}

        workers = min(config.client_num_per_round, ds.num_clients)
        if config.backend.lower() == "grpc":
            import socket

            from fedml_tpu.comm.grpc_backend import GRPCCommManager

            # an ephemeral-port probe only suggests a free BLOCK base; the
            # block can be raced before the ranks bind, so retry with a
            # fresh base on bind failure (run_ranks tears down partial
            # setups, so a retry starts clean)
            last_err = None
            for _ in range(3):
                with socket.socket() as s:
                    s.bind(("127.0.0.1", 0))
                    base = s.getsockname()[1]
                try:
                    agg = run_fedavg_edge(
                        ds, config, worker_num=workers,
                        comm_factory=lambda r: GRPCCommManager(
                            r, workers + 1, base_port=base, host="127.0.0.1",
                            codec=config.wire_codec))
                    break
                except OSError as e:
                    last_err = e
            else:
                raise last_err
        else:
            agg = run_fedavg_edge(ds, config, worker_num=workers)
        hist = agg.test_history
        result = {"round": [h["round"] for h in hist],
                  "Test/Acc": [h["acc"] for h in hist],
                  "Test/Loss": [h["loss"] for h in hist]}
        log.info("result %s", json.dumps({"rounds": len(hist)}))
        return result

    if algorithm == "fedgkt":
        from fedml_tpu.algorithms.fedgkt import FedGKTAPI

        from fedml_tpu.models.gkt import gkt_blocks_from_names

        blocks = (1, 2) if config.ci else gkt_blocks_from_names(
            config.model_client, config.model_server)
        # multi-chip: shard the server phase over all chips (the reference
        # auto-uses nn.DataParallel when GPUs allow, GKTServerTrainer.py:28-29).
        # Auto only on real accelerators — GSPMD-partitioning the server scan
        # is a large compile that virtual CPU meshes pay for with no speedup
        # (pass server_mesh explicitly to FedGKTAPI to force it anywhere).
        server_mesh = None
        import jax as _jax
        n_dev = len(_jax.devices())
        if (n_dev > 1 and ds.num_clients % n_dev == 0
                and _jax.default_backend() != "cpu"):
            from fedml_tpu.parallel.dataparallel import batch_mesh

            server_mesh = batch_mesh(n_dev)
        api = FedGKTAPI(ds, config, client_blocks=blocks[0],
                        server_blocks_per_stage=blocks[1],
                        server_mesh=server_mesh)
        return api.train()
    if algorithm in ("fednas", "crosssilo_fednas"):
        from fedml_tpu.algorithms.fednas import CrossSiloFedNASAPI, FedNASAPI

        size = dict(channels=4, layers=2, steps=2, multiplier=2) if config.ci \
            else dict(channels=16, layers=8, steps=4, multiplier=4)
        cls = CrossSiloFedNASAPI if algorithm == "crosssilo_fednas" else FedNASAPI
        return cls(ds, config, **size).train()
    if algorithm == "splitnn":
        from fedml_tpu.algorithms.split_nn import SplitNNAPI
        from fedml_tpu.models.split import create_split_cnn, create_split_mlp

        if len(ds.train_x.shape) == 5:  # [C, n, H, W, ch] image data
            cb, sb = create_split_cnn(ds.class_num, input_shape=ds.train_x.shape[2:])
        else:
            cb, sb = create_split_mlp(ds.class_num, input_shape=ds.train_x.shape[2:])
        return SplitNNAPI(ds, config, cb, sb).train()

    from fedml_tpu.algorithms.centralized import CentralizedTrainer
    from fedml_tpu.algorithms.decentralized import (
        DecentralizedFedAPI, MeshDecentralizedFedAPI,
    )
    from fedml_tpu.algorithms.fedagc import CrossSiloFedAGCAPI, FedAGCAPI
    from fedml_tpu.algorithms.fedavg import CrossSiloFedAvgAPI, FedAvgAPI
    from fedml_tpu.algorithms.fednova import CrossSiloFedNovaAPI, FedNovaAPI
    from fedml_tpu.algorithms.fedopt import CrossSiloFedOptAPI, FedOptAPI
    from fedml_tpu.algorithms.fedprox import CrossSiloFedProxAPI, FedProxAPI
    from fedml_tpu.algorithms.fedseg import CrossSiloFedSegAPI, FedSegAPI
    from fedml_tpu.algorithms.hierarchical import (
        CrossSiloHierarchicalFedAvgAPI, HierarchicalFedAvgAPI,
    )
    from fedml_tpu.algorithms.robust import CrossSiloFedAvgRobustAPI, FedAvgRobustAPI
    from fedml_tpu.algorithms.silo import SiloRunner
    from fedml_tpu.algorithms.streaming_fedavg import StreamingFedAvgAPI
    from fedml_tpu.algorithms.turboaggregate import TurboAggregateAPI

    simple = {
        "fedavg": FedAvgAPI,
        "streaming_fedavg": StreamingFedAvgAPI,
        "crosssilo_fedavg": CrossSiloFedAvgAPI,
        "crosssilo_fedopt": CrossSiloFedOptAPI,
        "crosssilo_fednova": CrossSiloFedNovaAPI,
        "crosssilo_fedagc": CrossSiloFedAGCAPI,
        "crosssilo_fedavg_robust": CrossSiloFedAvgRobustAPI,
        "crosssilo_fedprox": CrossSiloFedProxAPI,
        "fedopt": FedOptAPI,
        "fedprox": FedProxAPI,
        "fednova": FedNovaAPI,
        "fedagc": FedAGCAPI,
        "fedavg_robust": FedAvgRobustAPI,
        "hierarchical": HierarchicalFedAvgAPI,
        "crosssilo_hierarchical": CrossSiloHierarchicalFedAvgAPI,
        "decentralized": DecentralizedFedAPI,
        "crosssilo_decentralized": MeshDecentralizedFedAPI,
        "turboaggregate": TurboAggregateAPI,
        "fedseg": FedSegAPI,
        "crosssilo_fedseg": CrossSiloFedSegAPI,
        "centralized": CentralizedTrainer,
    }
    bundle = _bundle_for(config, ds)
    if algorithm in simple:
        result = simple[algorithm](ds, config, bundle).train()
    elif algorithm.startswith("silo_"):
        silo_cls = {
            "silo_fedavg": FedAvgAPI,
            "silo_fedopt": FedOptAPI,
            "silo_fednova": FedNovaAPI,
            "silo_fedagc": FedAGCAPI,
        }[algorithm]
        result = SiloRunner(ds, config, api_cls=silo_cls, bundle=bundle).train()
    else:  # pragma: no cover
        raise AssertionError(algorithm)
    log.info("result %s", json.dumps({k: v for k, v in dict(result).items()
                                      if isinstance(v, (int, float, str))}))
    return result
