"""Thin alias of the unified launcher (reference fedml_experiments pattern:
one main per algorithm). Equivalent to --algorithm streaming_fedavg —
FedAvg whose clients stream batches from host memory through the native
ordered pipeline (for datasets exceeding the device-residency budget)."""

import sys

from fedml_tpu.experiments.run import main

if __name__ == "__main__":
    main(sys.argv[1:], default_algorithm="streaming_fedavg")
