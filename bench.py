"""Flagship benchmark: FedAvg on CIFAR-10-shaped data with ResNet-56,
32 non-IID clients (BASELINE.md north-star config), standalone-simulation
paradigm on the available device (TPU when present).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: federated training throughput in REAL images/sec through local SGD
(the round is one jitted program: vmap over the sampled cohort of a
lax.scan over minibatch SGD steps + weighted aggregation; cohort-bucketing
trims the scan to the sampled cohort's real max size). Only the cohort's
real records count — masked padding steps are excluded, matching what the
reference's ragged Python loop would process.

vs_baseline: the reference publishes no throughput numbers (SURVEY.md §6),
so the baseline constant is an estimate of the reference stack on its own
headline hardware, 8xV100 (FedML paper, arXiv:2007.13518): 8 workers
training ResNet-56/CIFAR-10 in parallel at ~1500 img/s/GPU fp32 = 12000
img/s cluster-wide, ignoring its MPI state-dict exchange + 0.3 s/message
poll overhead (com_manager.py:78) — i.e., a GENEROUS baseline.

Measured complement (round 3): `tools/ref_bench.py` RUNS the reference's
execution model (torch, sequential clients, per-batch Python loop) on this
host's CPU next to fedml_tpu on the same CPU — measured numbers and the
honest backend attribution live in docs/perf.md §"Measured reference-stack
baseline". The 12k estimate stays as the vs_baseline divisor because the
single-CPU measurement cannot be extrapolated to the 8xV100 cluster.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

BASELINE_IMG_PER_SEC = 12000.0  # 8xV100 estimate, see module docstring

# FLOPs-and-peak accounting lives in fedml_tpu/obs/cost.py (fedcost) so the
# bench headline, tools/roofline_report.py and tools/trace_report.py share
# ONE peak table and ONE cost-model convention — `mfu`/`mfu_basis` are
# computed by the exact logic that used to live inline here. Imported
# lazily (fedml_tpu pulls in jax; keep module import light for tooling).


def _peak_flops(device):
    from fedml_tpu.obs.cost import peak_flops

    return peak_flops(device)


def _fwd_flops_per_image(bundle, variables, input_shape, batch, dtype):
    from fedml_tpu.obs.cost import fwd_flops_per_image

    return fwd_flops_per_image(bundle, variables, input_shape, batch, dtype)

# Bench config (north star: 32 non-IID clients, ResNet-56, CIFAR-10 shapes)
NUM_CLIENTS = 32
CLIENTS_PER_ROUND = 8
RECORDS_PER_CLIENT = 1562  # 50000/32
BATCH_SIZE = 64
EPOCHS = 1
MEASURE_ROUNDS = 5


def _bench_crosssilo(tiny: bool, model: str, rounds: int, batch: int,
                     clients_override: int | None = None):
    """Cross-silo distributed FedAvg on the same chip: full participation
    over a 1-device 'clients' mesh, resident-sharded data, psum aggregation.
    Reports its own real-images/sec so the mesh path's overhead vs the
    simulation paradigm is a measured number, not an assumption."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.algorithms.fedavg import CrossSiloFedAvgAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.synthetic import make_synthetic_classification
    from fedml_tpu.models import create_model
    from fedml_tpu.parallel.mesh import client_mesh

    # BENCH_CS_ALGO: measure a zoo algorithm through the same machinery
    # (the packed schedule carries the cross-silo hooks, so FedOpt/FedNova/
    # AGC ride it — this knob puts a number on that claim)
    algo = os.environ.get("BENCH_CS_ALGO", "fedavg")
    if algo != "fedavg":
        from fedml_tpu.algorithms.fedagc import CrossSiloFedAGCAPI
        from fedml_tpu.algorithms.fednova import CrossSiloFedNovaAPI
        from fedml_tpu.algorithms.fedopt import CrossSiloFedOptAPI

        classes = {
            "fedopt": CrossSiloFedOptAPI,
            "fednova": CrossSiloFedNovaAPI,
            "fedagc": CrossSiloFedAGCAPI,
        }
        if algo not in classes:
            raise ValueError(
                f"BENCH_CS_ALGO={algo!r}: choose one of "
                f"{['fedavg', *sorted(classes)]}")
        CrossSiloFedAvgAPI = classes[algo]

    # BENCH_CS_CLIENTS: silo-count override for the weak-scaling fit
    # (docs/perf.md): per-client records stay constant, so round compute
    # scales with the count and T(c) = a + b*c can be fitted from whole runs.
    clients = 4 if tiny else int(
        clients_override or os.environ.get("BENCH_CS_CLIENTS", NUM_CLIENTS))
    records = 8 if tiny else RECORDS_PER_CLIENT
    ds = make_synthetic_classification(
        "cifar10-bench-cs", (32, 32, 3), 10, clients,
        records_per_client=records,
        partition_method="homo" if tiny else "hetero",
        partition_alpha=0.5, batch_size=batch, seed=0,
    )
    cfg = FedConfig(
        model=model, dataset="cifar10", client_num_in_total=clients,
        client_num_per_round=clients,     # full participation: silo standard
        comm_round=rounds, batch_size=batch, epochs=EPOCHS, lr=0.1,
        momentum=0.9, dtype="bfloat16", frequency_of_the_test=10_000,
        seed=0, async_rounds=True,
        # the grouped mesh schedule (strip-dealt clients, per-group scan
        # lengths) is the measured configuration, like the sim paradigm's
        bucket_groups=int(os.environ.get("BENCH_BUCKET_GROUPS", "6")),
        # packed mesh schedule: 2 lanes/device measured best at 32 silos
        # (docs/mfu_experiments.md H5); 0 restores the grouped schedule
        pack_lanes=int(os.environ.get("BENCH_PACK_LANES_CS", "2")),
        # super-step: fold H rounds into one scanned program (H7 lever;
        # H=rounds makes the measured pass exactly one program)
        rounds_per_step=int(os.environ.get("BENCH_CS_SUPERSTEP", "1")),
        # force residency even on the CPU smoke path so tiny mode exercises
        # the same resident-sharded branch the TPU run measures
        device_data="on",
    )
    bundle = create_model(model, 10, dtype=jnp.bfloat16,
                          input_shape=ds.train_x.shape[2:],
                          bn_impl=os.environ.get("BENCH_BN", "xla"),
                          conv_impl=os.environ.get("BENCH_CONV", "xla"))
    api = CrossSiloFedAvgAPI(ds, cfg, bundle, mesh=client_mesh(1))
    # warm TWICE: the first pass's outputs carry fresh shardings, so the
    # second pass triggers one more trace/compile specialization — it must
    # land in the warm-up, not the measured pass (bit hard with the
    # super-step, whose single block call per pass hides it otherwise)
    for _pass in range(2):
        for r in range(1, rounds + 1):
            last = api.run_round(r)
        float(last)
    t0 = time.perf_counter()
    for r in range(1, rounds + 1):
        last = api.run_round(r)
    float(last)
    dt = time.perf_counter() - t0
    real = padded = 0
    for r in range(1, rounds + 1):
        re, pa = api.round_counts(r)
        real += re * EPOCHS
        padded += pa * EPOCHS
    return {
        "paradigm": "crosssilo shard_map psum, full participation, "
                    "resident-sharded, grouped scan schedule",
        "algorithm": algo,
        "clients": clients,
        "grouped_schedule": api._group_plan is not None,
        "packed_schedule": api._packed_mesh is not None,
        "images_per_sec": round(real / dt, 1),
        "padded_images_per_sec": round(padded / dt, 1),
        "rounds_per_sec": round(rounds / dt, 4),
    }


def _bench_packed_conv_ab(ds, base_cfg, model: str, rounds: int, peak):
    """fedpack flagship A/B (ops/packed_conv.py): the SAME packed-schedule
    round measured under the per-lane vmap lowering ('off') and the
    client-packed lowering (BENCH_PACKED_CONV_MODE, default 'blockdiag') —
    per-lowering real img/s, the packed program's static output-lane
    ceiling (the lift the packing buys) and, when a TPU peak is known,
    measured USEFUL-basis MFU vs that ceiling. On the CPU container this
    block is a structural/no-regression check (the >=1.5x img/s claim is
    asserted only on the TPU bench host, docs/perf.md 'Client packing')."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.models import create_model
    from fedml_tpu.obs import cost as fedcost

    mode = os.environ.get("BENCH_PACKED_CONV_MODE", "blockdiag")

    def measure_arms(api_cls, pick_table, cfg_extra=None):
        """One A/B (off vs ``mode``) through the shared measurement
        discipline — two warm passes, one timed pass, real-img/s +
        static-ceiling + roofline per arm — so the sgd flagship and the
        adaptive arm below stay comparable in the same JSON tail."""
        res = {"img_per_sec": {}, "mfu_vs_lane_ceiling": {},
               "mfu_mac_useful": {}}
        ceilings = {}
        for arm in dict.fromkeys(("off", mode)):
            # force residency so the CPU smoke exercises the same packed
            # (device-resident) schedule branch the TPU run measures
            cfg = base_cfg.replace(packed_conv=arm, device_data="on",
                                   **(cfg_extra or {}))
            bundle = create_model(
                model, 10, dtype=jnp.bfloat16,
                input_shape=ds.train_x.shape[2:],
                bn_impl=os.environ.get("BENCH_BN", "xla"),
                conv_impl=os.environ.get("BENCH_CONV", "xla"))
            fedcost.reset_cost_tables()
            api = api_cls(ds, cfg, bundle)
            for _pass in range(2):    # same two-pass warm as the headline
                for r in range(1, rounds + 1):
                    last = api.run_round(r)
                float(last)
            t0 = time.perf_counter()
            for r in range(1, rounds + 1):
                last = api.run_round(r)
            float(last)
            dt = time.perf_counter() - t0
            real = sum(api.round_counts(r)[0] for r in range(1, rounds + 1))
            res["img_per_sec"][arm] = round(real * EPOCHS / dt, 1)
            rec = pick_table()
            if rec is not None:
                ceilings[arm] = rec["summary"]["out_lane_ceiling"]
                rf = fedcost.roofline(rec["summary"], dt, invocations=rounds,
                                      peak=peak)
                res["mfu_vs_lane_ceiling"][arm] = rf.get("mfu_vs_ceiling")
                res["mfu_mac_useful"][arm] = rf.get("mfu_mac_useful",
                                                    rf.get("mfu_mac"))
        off, on = res["img_per_sec"].get("off"), res["img_per_sec"].get(mode)
        res["speedup"] = round(on / off, 3) if (off and on) else None
        # the packed program's static ceiling — the lane lift the packing
        # buys (bench_report tracks this across the artifact series)
        res["out_lane_ceiling"] = ceilings.get(mode)
        res["off_lane_ceiling"] = ceilings.get("off")
        return res

    def biggest_table():
        return max(fedcost.cost_tables().values(),
                   key=lambda r: r["summary"]["gemm_flops_per_invocation"],
                   default=None)

    out = dict({"mode": mode}, **measure_arms(FedAvgAPI, biggest_table))

    # fedplan (ISSUE 18): when the measured arm is `auto`, embed the plan
    # the run resolved — per-stage picks, predicted vs uniform ceilings —
    # so the artifact records WHY the arm lowered the way it did
    # (bench_report's `plan` column reads the summary string back)
    if mode == "auto":
        from fedml_tpu.parallel.packed import (packed_fallback_reason,
                                               resolve_packed_conv)

        bundle = create_model(model, 10, dtype=jnp.bfloat16,
                              input_shape=ds.train_x.shape[2:],
                              bn_impl=os.environ.get("BENCH_BN", "xla"),
                              conv_impl=os.environ.get("BENCH_CONV", "xla"))
        resolved = resolve_packed_conv(
            "auto", bundle, int(base_cfg.pack_lanes),
            optimizer=base_cfg.client_optimizer)
        out["plan"] = (
            {"resolved": resolved,
             "reason": packed_fallback_reason(bundle, "auto",
                                              base_cfg.client_optimizer)}
            if isinstance(resolved, str) else resolved.to_dict())

    # packed-everywhere (ISSUE 12): one ADAPTIVE arm through the identical
    # harness — FedOpt with a stateful server optimizer rides the same
    # packed round program (hooks + threaded server state), so its
    # per-lowering img/s and static ceiling land in the tail next to the
    # sgd flagship's. BENCH_PACKED_CONV_OPT names the server optimizer
    # ('off' disables the arm); bench_report's `fedopt ceiling` column is
    # missing-key tolerant for pre-ISSUE-12 artifacts.
    server_opt = os.environ.get("BENCH_PACKED_CONV_OPT", "adam")
    if server_opt not in ("", "off", "0"):
        from fedml_tpu.algorithms.fedopt import FedOptAPI

        def fedopt_table():
            # the class-qualified record for exactly the program measured
            return (fedcost.table_for("packed_step.FedOptAPI")
                    or biggest_table())

        out["fedopt"] = dict(
            {"server_optimizer": server_opt},
            **measure_arms(FedOptAPI, fedopt_table,
                           {"server_optimizer": server_opt}))
    return out


def _bench_crossdevice_r05_basis(tiny: bool):
    """Cross-device paradigm at the reference's own scale: 342,477 logical
    clients, 50 sampled per round (stackoverflow row,
    reference benchmark/README.md:57). The client stack is virtual
    (data/crossdevice.py) — each round materializes ONLY its cohort
    host-side and ships it; this row measures that whole sampled path:
    sampling at 342k, cohort materialization, host->device, the round
    program, aggregation. Measured as a host-round-pipeline A/B:
    --host_pipeline_depth 0 (serial) vs BENCH_XDEV_DEPTH (default 2)
    prefetched rounds, with stage timings (utils/metrics.round_stats).
    Since ISSUE 13 this is the SAME-HOST BASIS row the fedsched block's
    uplift is judged against (the r05 artifact's 46.8 clients/s operating
    point, re-measured on whatever host runs this bench)."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data import load_dataset
    from fedml_tpu.models import create_model
    from fedml_tpu.utils.metrics import round_stats

    clients = 1000 if tiny else int(
        os.environ.get("BENCH_XDEV_CLIENTS", "342477"))
    cohort = 10 if tiny else 50
    rounds = 1 if tiny else 3
    depth = int(os.environ.get("BENCH_XDEV_DEPTH", "2"))
    ds = load_dataset("stackoverflow_lr_full", client_num_in_total=clients,
                      batch_size=10)
    bundle = create_model("lr", ds.class_num, input_shape=ds.train_x.shape[2:])

    from fedml_tpu.obs import pulse_if_enabled

    plane = pulse_if_enabled()

    def measure(pipeline_depth: int):
        cfg = FedConfig(
            model="lr", dataset="stackoverflow_lr",
            client_num_in_total=clients, client_num_per_round=cohort,
            comm_round=rounds, batch_size=10, epochs=1, lr=0.05, seed=0,
            frequency_of_the_test=10_000,
            # bf16 halves the dominant cost of this row: the per-round
            # uplink of the materialized cohort (10k-dim features, 140 MB
            # as f32)
            dtype="bfloat16", async_rounds=True,
            host_pipeline_depth=pipeline_depth,
            host_pipeline_workers=int(
                os.environ.get("BENCH_XDEV_WORKERS", "0")))
        api = FedAvgAPI(ds, cfg, bundle)
        for r in range(1, rounds + 1):      # warm the compile
            last = api.run_round(r)
        float(last)
        api._stage_rows.clear()
        ds.materialized_rows = 0
        pf = api._host_prefetcher()
        if pf is not None:
            # steady state for the measured window: in a long run every
            # round is prefetched during its predecessor; without this the
            # window's FIRST round pays a cold on-demand build and a
            # 3-round measurement understates the pipeline by ~1/3
            pf.prime(1, wait=True)
        # fresh per-client profiles for the MEASURED window only: the warm
        # rounds above (and the other A/B arm's identical cohorts) would
        # otherwise double participation counts and seed EMA train-ms with
        # compile-dominated warmup walls
        if plane is not None and plane.profiler is not None:
            plane.profiler.reset()
        t0 = time.perf_counter()
        for r in range(1, rounds + 1):
            last = api.run_round(r)
        float(last)
        dt = time.perf_counter() - t0
        real = sum(api.round_counts(r)[0] for r in range(1, rounds + 1))
        row = {
            "rounds_per_sec": round(rounds / dt, 4),
            "clients_per_sec": round(rounds * cohort / dt, 2),
            "examples_per_sec": round(real / dt, 1),
            # with the pipeline on this includes speculative prefetches of
            # rounds past the measured window — real work the pipeline does
            "materialized_rows": int(ds.materialized_rows),
            "stage": round_stats(api._stage_rows, pipeline_depth),
        }
        api.close()
        return row

    off = measure(0)
    on = measure(depth) if depth > 0 else None
    head = on or off
    # fedpulse profiler aggregates of the HEAD arm (the last measured):
    # per-client EMA train-ms spread, participation fairness, store bytes,
    # and the fedsketch percentile lanes (p50/p90/p99 train-ms etc — the
    # `sketches` block tools/bench_report.py's p99 trajectory columns read)
    # — the live-telemetry evidence at the 342k-client operating point
    profiler_agg = plane.aggregates() if plane is not None else None
    return {
        "paradigm": "cross-device sampled materialization (virtual client "
                    "stack, O(cohort) memory, host round pipeline)",
        "clients_total": clients,
        "clients_per_round": cohort,
        "rounds_per_sec": head["rounds_per_sec"],
        "clients_per_sec": head["clients_per_sec"],
        "examples_per_sec": head["examples_per_sec"],
        "materialized_rows": head["materialized_rows"],
        "device_resident": False,
        "profiler": profiler_agg,
        "pipeline_ab": {
            "off": off, "on": on, "depth": depth,
            "speedup": (round(on["rounds_per_sec"] / off["rounds_per_sec"], 3)
                        if on else None),
        },
    }


def _bench_fedsched(tiny: bool):
    """fedsched (ISSUE 13): the scheduled, streaming cross-device round
    path at MILLION-client scale — thousand-client cohorts streamed
    through the O(1) accumulator in packed-lane sub-cohort chunks, with a
    cohort-policy A/B (uniform vs speed).

    Three arms on one million-client synthetic cross-device stack
    (lognormal per-client record counts — the heterogeneity the policy
    schedules against):

    - ``cohort50_batch``: today's path (uniform draw, batch aggregation)
      at the r05 operating point's cohort — the same-dataset scaling basis;
    - ``streamed_uniform``: 1000-client cohorts in ``--cohort_chunk``
      packed-lane chunks folded into the streaming accumulator, uniform
      draw — isolates cohort-scale + streaming;
    - ``streamed_speed``: + ``--cohort_policy speed`` over the population
      count prior (``snapshot_from_counts``: every client's dataset size
      is registration-time metadata; ``ms_per_record`` is calibrated from
      the streamed_uniform arm's measured per-client EMA when the pulse
      profiler is on) — the policy A/B's treatment arm.

    Per arm: clients/s, examples/s (the speed policy trades per-round
    example mass for round rate — both reported), the fedsketch p99
    train-ms tail (shrinks under ``speed``), and the streaming
    accumulator's measured bytes (O(1) in cohort size)."""
    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.crossdevice import make_synthetic_crossdevice
    from fedml_tpu.data.sched import snapshot_from_counts
    from fedml_tpu.models import create_model
    from fedml_tpu.obs import pulse_if_enabled

    clients = 20_000 if tiny else int(
        os.environ.get("BENCH_SCHED_CLIENTS", "1000000"))
    cohort = 40 if tiny else int(
        os.environ.get("BENCH_SCHED_COHORT", "1000"))
    chunk = 10 if tiny else int(os.environ.get("BENCH_SCHED_CHUNK", "250"))
    lanes = int(os.environ.get("BENCH_SCHED_LANES", "4"))
    # measured best at depth 0 on a 1-core host (the pipeline thread
    # contends with the chunk programs); >0 overlaps chunk materialization
    # on hosts with cores to spare
    depth = int(os.environ.get("BENCH_SCHED_DEPTH", "0"))
    rounds = 1 if tiny else 3
    dim, classes = (64, 8) if tiny else (1024, 32)
    ds = make_synthetic_crossdevice(
        "xdev-sched", dim, classes, clients, batch_size=8,
        mean_records=12.0, max_records=96, seed=0)
    bundle = create_model("lr", ds.class_num, input_shape=(dim,))
    plane = pulse_if_enabled()

    def measure(label, cohort_n, policy="uniform", streaming=False,
                snapshot=None):
        cfg = FedConfig(
            model="lr", dataset="xdev-sched",
            client_num_in_total=clients, client_num_per_round=cohort_n,
            comm_round=rounds, batch_size=8, epochs=1, lr=0.1, seed=0,
            frequency_of_the_test=10_000, async_rounds=True,
            cohort_policy=policy,
            stream_aggregate="deterministic" if streaming else "off",
            cohort_chunk=chunk if streaming else 0,
            pack_lanes=lanes if streaming else 0,
            host_pipeline_depth=depth if streaming else 0)
        api = FedAvgAPI(ds, cfg, bundle)
        if snapshot is not None:
            # static signal BEFORE the warm pass: warm and measured rounds
            # must compile/run the identical scheduled cohorts
            api.set_cohort_profiler(snapshot)
        for r in range(1, rounds + 1):
            last = api.run_round(r)
        float(last)
        if plane is not None and plane.profiler is not None:
            plane.profiler.reset()   # profile the measured pass only
        t0 = time.perf_counter()
        for r in range(1, rounds + 1):
            last = api.run_round(r)
        float(last)
        dt = time.perf_counter() - t0
        real = sum(api.round_counts(r)[0] for r in range(1, rounds + 1))
        row = {
            "arm": label,
            "clients_per_round": cohort_n,
            "policy": policy,
            "stream_aggregate": cfg.stream_aggregate,
            "rounds_per_sec": round(rounds / dt, 4),
            "clients_per_sec": round(rounds * cohort_n / dt, 2),
            "examples_per_sec": round(real / dt, 1),
        }
        if plane is not None and plane.profiler is not None:
            sk = plane.profiler.sketch_summaries().get("train_ms") or {}
            row["p99_train_ms"] = sk.get("p99")
            row["p50_train_ms"] = sk.get("p50")
        if api.stream_stats is not None:
            row["stream"] = dict(api.stream_stats)
        api.close()
        return row

    basis = measure("cohort50_batch", min(50, cohort))
    uniform = measure("streamed_uniform", cohort, streaming=True)
    # count-prior snapshot for the speed arm: ms_per_record calibrated
    # from the uniform arm's measured per-client EMAs when available
    # (the prior's RANKING is scale-invariant, so 1.0 is a safe fallback)
    ms_per_record = 1.0
    if plane is not None and plane.profiler is not None:
        snap = plane.profiler.snapshot()
        if snap.n_seen:
            seen_counts = np.asarray(ds.train_counts)[snap.ids]
            ok = seen_counts > 0
            if ok.any():
                ms_per_record = float(np.median(
                    snap.ema_train_ms[ok] / seen_counts[ok]))
    prior = snapshot_from_counts(ds.train_counts, ms_per_record)
    speed = measure("streamed_speed", cohort, policy="speed",
                    streaming=True, snapshot=prior)
    return {
        "clients_total": clients,
        "clients_per_round": cohort,
        "cohort_chunk": chunk,
        "pack_lanes": lanes,
        "policy": "speed",
        "stream_aggregate": "deterministic",
        "ms_per_record_prior": round(ms_per_record, 6),
        "arms": [basis, uniform, speed],
        # the policy A/B: clients/s uplift and the shrinking p99 tail
        "policy_uplift_clients_per_sec": round(
            speed["clients_per_sec"] / uniform["clients_per_sec"], 3),
        "p99_train_ms": {"uniform": uniform.get("p99_train_ms"),
                         "speed": speed.get("p99_train_ms")},
        "accumulator_bytes": (speed.get("stream") or {}).get(
            "accumulator_bytes"),
    }


def _bench_fedbuff(tiny: bool):
    """fedbuff (ISSUE 14): sync-vs-async A/B under injected stragglers.

    One small edge federation (threads, local transport), three arms on
    the same dataset/model with the same per-message chaos delay — the
    WAN-like iid latency whose per-round MAX gates a synchronous round:

    - ``sync``: fedavg_edge rounds (strict barrier) — every round pays the
      slowest worker's down+up latency;
    - ``async_uniform``: fedbuff arrival mode, ``buffer_k = workers`` —
      folds land at each worker's OWN pace, so a version emits as soon as
      any K contributions arrive and the latency tail stops gating;
    - ``async_speed``: + ``--cohort_policy speed`` over the count prior
      (async dispatch composes with the fedsched CohortScheduler).

    Per arm: clients/s (logical client trainings per wall second — the
    async acceptance is async >= sync under the same injected delay) and
    the version-lag p99 from the fold log (the staleness the decay
    weighting absorbed instead of dropping)."""
    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.synthetic import make_synthetic_classification
    from fedml_tpu.distributed.fedavg_edge import run_fedavg_edge
    from fedml_tpu.distributed.fedbuff_edge import run_fedbuff_edge

    workers = int(os.environ.get("BENCH_FEDBUFF_WORKERS", "3"))
    cohort = workers * 2            # every fold trains exactly 2 clients
    delay = 40.0 if tiny else float(
        os.environ.get("BENCH_FEDBUFF_DELAY_MS", "120"))
    versions = 3 if tiny else int(
        os.environ.get("BENCH_FEDBUFF_VERSIONS", "8"))
    dim = 16 if tiny else 64
    ds = make_synthetic_classification(
        "fedbuff-bench", (dim,), 5, cohort, records_per_client=24,
        partition_method="hetero", partition_alpha=0.5, batch_size=8,
        seed=0)

    def cfg(**kw):
        base = dict(
            model="lr", dataset="fedbuff-bench", client_num_in_total=cohort,
            client_num_per_round=cohort, comm_round=versions, batch_size=8,
            epochs=1, lr=0.1, seed=0, frequency_of_the_test=10_000,
            device_data="off")
        base.update(kw)
        return FedConfig(**base)

    # absorb the jitted local-train compile OUTSIDE the timed arms (both
    # paradigms share the jit signature, so one warm run serves all)
    run_fedavg_edge(ds, cfg(comm_round=1), worker_num=workers)

    chaos = dict(chaos_delay_ms=delay, chaos_seed=3)

    def measure(label, runner, **kw):
        t0 = time.perf_counter()
        agg = runner(ds, cfg(**chaos, **kw), worker_num=workers)
        dt = time.perf_counter() - t0
        row = {"arm": label, "wall_s": round(dt, 3)}
        if hasattr(agg, "buffer"):
            trained = agg.uploads_folded * (cohort // workers)
            stal = [r["staleness"] for r in agg.buffer.fold_log]
            row.update({
                "versions": agg.versions_emitted,
                "folds": agg.uploads_folded,
                "clients_per_sec": round(trained / dt, 2),
                "version_lag_p99": (round(float(
                    np.percentile(stal, 99)), 3) if stal else None),
                "version_lag_mean": (round(float(np.mean(stal)), 4)
                                     if stal else None),
            })
        else:
            row.update({
                "rounds": versions,
                "clients_per_sec": round(versions * cohort / dt, 2),
            })
        return row

    sync = measure("sync", run_fedavg_edge)
    uniform = measure("async_uniform", run_fedbuff_edge,
                      buffer_k=workers, buffer_mode="arrival")
    from fedml_tpu.data.sched import snapshot_from_counts

    counts = np.asarray([float(ds.client_slice_cached(c)[3][0])
                         for c in range(cohort)])
    speed = measure("async_speed",
                    lambda d, c, worker_num: run_fedbuff_edge(
                        d, c, worker_num=worker_num,
                        profile_snapshot=snapshot_from_counts(counts)),
                    buffer_k=workers, buffer_mode="arrival",
                    cohort_policy="speed")
    return {
        "workers": workers,
        "buffer_k": workers,
        "buffer_mode": "arrival",
        "delay_ms": delay,
        "versions": versions,
        "arms": [sync, uniform, speed],
        "sync_clients_per_sec": sync["clients_per_sec"],
        "async_clients_per_sec": uniform["clients_per_sec"],
        "async_vs_sync": round(
            uniform["clients_per_sec"] / sync["clients_per_sec"], 3),
        "version_lag_p99": uniform.get("version_lag_p99"),
    }


def _bench_gateway(tiny: bool):
    """fedgate (ISSUE 16): multi-tenant gateway scaling + noisy neighbor.

    One in-process gateway (distributed/gateway.py, local transport)
    multiplexing N concurrent federations over one shared listener, at
    N = 1/4/8 tenants (tiny: 1/2). At every multi-tenant point the FIRST
    tenant is a noisy neighbor — 30% seeded drop chaos — whose retransmit
    storm hits the same shared listener as everyone else; the lanes are
    capped (``wire_inbox_cap``) so flow control actually engages.

    Per point: aggregate and per-tenant rounds/s, the flow-control counts
    (WIRE_BUSY push-backs + stale sheds — the load the cap absorbed,
    never silently), and the p99 upload latency a HEALTHY tenant's pulse
    sketch recorded while the neighbor misbehaved — the isolation
    headline: how much tail latency one tenant's chaos costs another."""
    import shutil
    import tempfile

    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.synthetic import make_synthetic_classification
    from fedml_tpu.distributed.fedavg_edge import run_fedavg_edge
    from fedml_tpu.distributed.gateway import run_gateway

    workers = 2 if tiny else int(os.environ.get("BENCH_GATEWAY_WORKERS",
                                                "13"))
    tenant_points = (1, 2) if tiny else (1, 4, 8)
    rounds = 2
    cap = max(2, workers // 2)
    cohort = workers * 2
    dim = 16 if tiny else 64
    ds = make_synthetic_classification(
        "gateway-bench", (dim,), 5, cohort, records_per_client=16,
        partition_method="hetero", partition_alpha=0.5, batch_size=8,
        seed=0)

    def cfg(**kw):
        base = dict(
            model="lr", dataset="gateway-bench", client_num_in_total=cohort,
            client_num_per_round=cohort, comm_round=rounds, batch_size=8,
            epochs=1, lr=0.1, seed=0, frequency_of_the_test=10_000,
            device_data="off", wire_reliable=True, wire_inbox_cap=cap,
            wire_retry_base_s=0.02, wire_retry_max=8)
        base.update(kw)
        return FedConfig(**base)

    # absorb the jitted local-train compile OUTSIDE the timed points
    run_fedavg_edge(ds, cfg(comm_round=1, wire_inbox_cap=0),
                    worker_num=workers)

    def _last_snap(path):
        last = {}
        try:
            with open(path) as f:
                for line in f:
                    try:
                        s = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(s, dict) and "round" in s:
                        last = s
        except OSError:
            pass
        return last

    def point(n_tenants):
        pulse_dir = tempfile.mkdtemp(prefix="bench-gw-")
        tenants = []
        for i in range(n_tenants):
            kw = {}
            if n_tenants > 1 and i == 0:
                # the noisy neighbor: 30% drop on tenant 0's wire
                kw = dict(chaos_drop=0.3, chaos_dup=0.1, chaos_seed=11)
            tenants.append((f"t{i}", ds, cfg(**kw), workers))
        t0 = time.perf_counter()
        res = run_gateway(tenants, transport="local", timeout=600.0,
                          pulse_dir=pulse_dir, max_tenants=n_tenants)
        dt = time.perf_counter() - t0
        healthy = res[f"t{n_tenants - 1}"]   # never the noisy one
        sk = (_last_snap(healthy["pulse_path"]).get("sketches") or {})
        busy = sum(r["wire"].get("gw_busy_sent", 0) for r in res.values())
        shed = sum(r["wire"].get("gw_shed_stale", 0) for r in res.values())
        row = {
            "tenants": n_tenants,
            "workers": n_tenants * workers,
            "wall_s": round(dt, 3),
            "rounds_per_sec_per_tenant": round(rounds / dt, 3),
            "rounds_per_sec_total": round(n_tenants * rounds / dt, 3),
            "busy_sent": busy,
            "shed_stale": shed,
            "healthy_upload_p99_ms": (sk.get("upload_ms") or {}).get("p99"),
            "errors": [f"{t}: {r['error']}" for t, r in res.items()
                       if r["error"]],
        }
        shutil.rmtree(pulse_dir, ignore_errors=True)
        return row

    points = [point(n) for n in tenant_points]
    top = points[-1]
    return {
        "workers_per_tenant": workers,
        "rounds": rounds,
        "inbox_cap": cap,
        "noisy_chaos_drop": 0.3,
        "scale": points,
        "tenants": top["tenants"],
        "rounds_per_sec_per_tenant": top["rounds_per_sec_per_tenant"],
        "rounds_per_sec_total": top["rounds_per_sec_total"],
        "busy_sent": top["busy_sent"],
        "shed_stale": top["shed_stale"],
        "healthy_upload_p99_ms": top["healthy_upload_p99_ms"],
    }


def _bench_crossdevice(tiny: bool):
    """The cross-device block since ISSUE 13: headline numbers come from
    the fedsched scheduled+streamed path at million-client scale (the
    ``streamed_speed`` arm), with the r05 stackoverflow operating point
    re-measured in the same run as the same-host basis the uplift is
    judged against (the archived r05 artifact's 46.8 clients/s was a
    different host; clients/s only compares within one run). Since ISSUE
    14 it also carries the fedbuff sync-vs-async block — LAST, because the
    edge launchers' ``configure_from`` tears down the bench's profiler-only
    pulse plane (pulse_path is authoritative), and every plane consumer
    above has snapshotted by then."""
    basis = _bench_crossdevice_r05_basis(tiny)
    sched = _bench_fedsched(tiny)
    fedbuff = None
    if not os.environ.get("BENCH_NO_FEDBUFF"):
        fedbuff = _bench_fedbuff(tiny)
    # fedgate (ISSUE 16) runs after fedbuff, same caveat: its warm run is
    # an edge launcher whose configure_from tears down the bench pulse
    # plane (run_gateway itself streams to its own per-tenant planes)
    gateway = None
    if not os.environ.get("BENCH_NO_GATEWAY"):
        gateway = _bench_gateway(tiny)
    head = sched["arms"][-1]      # streamed_speed
    return {
        "paradigm": "cross-device scheduled streaming rounds (fedsched: "
                    "profiler-scheduled cohorts, O(1) streaming "
                    "aggregation, packed-lane sub-cohort chunks)",
        "clients_total": sched["clients_total"],
        "clients_per_round": sched["clients_per_round"],
        "policy": sched["policy"],
        "rounds_per_sec": head["rounds_per_sec"],
        "clients_per_sec": head["clients_per_sec"],
        "examples_per_sec": head["examples_per_sec"],
        "device_resident": False,
        "fedsched": sched,
        "fedbuff": fedbuff,
        "gateway": gateway,
        "r05_basis": basis,
        "uplift_vs_r05_basis": (
            round(head["clients_per_sec"] / basis["clients_per_sec"], 2)
            if basis.get("clients_per_sec") else None),
    }


def main():
    import jax
    import jax.numpy as jnp

    # Persistent compilation cache: the bench compiles one XLA program per
    # distinct round plan (cohort bucket/group tuple); caching makes repeat
    # bench invocations skip straight to the measured pass.
    if not os.environ.get("BENCH_NO_CACHE"):
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(os.path.dirname(__file__) or ".",
                                       ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from fedml_tpu.core.config import FedConfig
    from fedml_tpu.data.synthetic import make_synthetic_classification
    from fedml_tpu.algorithms.fedavg import FedAvgAPI
    from fedml_tpu.models import create_model
    from fedml_tpu.obs import cost as fedcost

    # fedcost roofline attribution: every round program the bench builds
    # (sim packed/grouped steps, the mesh packed round, the super-step fn)
    # is lowered once more at build time and its per-op GEMM/lane-fill
    # table recorded — pure tracing during the WARMUP pass, so the timed
    # pass is untouched. BENCH_NO_ROOFLINE=1 opts out.
    if not os.environ.get("BENCH_NO_ROOFLINE"):
        fedcost.reset_cost_tables()   # this run's programs only
        fedcost.enable_cost_attribution(True)

    # fedpulse: a profiler-only plane (no pulse stream unless
    # BENCH_PULSE_PATH names one) so the tail carries end-of-run per-client
    # aggregates — participation fairness and EMA train-ms spread become
    # part of the TPU-host trajectory. BENCH_NO_PULSE=1 opts out.
    from fedml_tpu.obs import live as fedpulse

    pulse_plane = None
    if not os.environ.get("BENCH_NO_PULSE"):
        pulse_plane = fedpulse.configure(
            os.environ.get("BENCH_PULSE_PATH"), profile_store=True)

    # fedlens: arm the learning-signal lane for the flagship pass — output-
    # only reductions riding the round program (bit-identical weights,
    # obs/lens.py), so the tail carries the per-client update-norm/drift
    # distribution tails at the flagship operating point. Needs the pulse
    # plane (its profiler owns the sketch lanes). BENCH_NO_LENS=1 opts out.
    from fedml_tpu.obs import lens as fedlens

    if pulse_plane is not None and not os.environ.get("BENCH_NO_LENS"):
        fedlens.configure(True)

    # BENCH_SCALE=tiny: CI/CPU smoke of the same code path (not a benchmark).
    tiny = os.environ.get("BENCH_SCALE") == "tiny"
    model = os.environ.get("BENCH_MODEL", "resnet56")
    records = 8 if tiny else RECORDS_PER_CLIENT
    rounds = 1 if tiny else MEASURE_ROUNDS
    batch = int(os.environ.get("BENCH_BATCH", 8 if tiny else BATCH_SIZE))
    cohort = 2 if tiny else CLIENTS_PER_ROUND

    ds = make_synthetic_classification(
        "cifar10-bench", (32, 32, 3), 10, NUM_CLIENTS,
        records_per_client=records,
        partition_method="homo" if tiny else "hetero",
        partition_alpha=0.5, batch_size=batch, seed=0,
    )
    cfg = FedConfig(
        model=model, dataset="cifar10", client_num_in_total=NUM_CLIENTS,
        client_num_per_round=cohort, comm_round=rounds,
        batch_size=batch, epochs=EPOCHS, lr=0.1, momentum=0.9,
        dtype="bfloat16", frequency_of_the_test=10_000, seed=0,
        bucket_groups=int(os.environ.get("BENCH_BUCKET_GROUPS", "6")),
        # packed schedule (parallel/packed.py): 2 lanes measured best for
        # the cohort-8 sim round — round-4 campaign, docs/mfu_experiments.md
        # H5 (0 restores the grouped/bucketed schedule)
        pack_lanes=int(os.environ.get("BENCH_PACK_LANES", "2")),
        scan_unroll=int(os.environ.get("BENCH_UNROLL", "1")),
        cohort_vmap_width=int(os.environ.get("BENCH_COHORT_WIDTH", "0")),
        # rounds return device-scalar losses (no per-round host sync): the
        # timed loop pipelines dispatches and blocks ONCE at the end, so the
        # remote-dispatch latency (~100 ms/sync through the tunnel) overlaps
        # with device compute instead of serializing after it
        async_rounds=True,
    )
    bundle = create_model(model, 10, dtype=jnp.bfloat16,
                          input_shape=ds.train_x.shape[2:],
                          bn_impl=os.environ.get("BENCH_BN", "xla"),
                          conv_impl=os.environ.get("BENCH_CONV", "xla"))
    api = FedAvgAPI(ds, cfg, bundle)

    # Warmup pass: run every measured round once so each distinct cohort
    # bucket's XLA program is compiled before the timed pass (run_round(r)
    # samples deterministically from r, so the timed pass reuses the exact
    # same programs — warm exactly the measured rounds 1..N).
    # (async_rounds: no per-round sync — the trailing float() barriers.)
    # NB: block_until_ready on tunnel-backed arrays returns without waiting
    # (remote async completion), so the end-of-pass barrier is float() of the
    # LAST round's loss — it data-depends on every prior round, and pulling
    # the scalar to host genuinely blocks.
    for r in range(1, rounds + 1):
        last = api.run_round(r)
    float(last)

    # profile the MEASURED pass only: the warmup pass above already fed the
    # same cohorts (participation would double, EMA would blend compiles)
    if pulse_plane is not None and pulse_plane.profiler is not None:
        pulse_plane.profiler.reset()
    t0 = time.perf_counter()
    for r in range(1, rounds + 1):
        last = api.run_round(r)
    float(last)  # one sync for the whole pipelined pass
    dt = time.perf_counter() - t0

    # Real images trained in the measured period (padding steps are masked
    # no-ops and do not count), plus the padded count for the curious.
    # round_counts reports the same plan run_round executed — one source
    # of truth for the throughput accounting.
    real_images = padded_images = 0
    for r in range(1, rounds + 1):
        real, padded = api.round_counts(r)
        real_images += real * EPOCHS
        padded_images += padded * EPOCHS

    img_per_sec = real_images / dt
    rounds_per_sec = rounds / dt

    # MFU accounting: fwd FLOPs/image from XLA's cost model, x3 for the
    # training step (fwd + ~2x bwd). Executed compute = the PADDED rate
    # (masked padding steps still burn MXU cycles), so
    # mfu = padded_rate * train_flops_per_image / device bf16 peak — the
    # honest device-utilization number for the roofline discussion
    # (VERDICT r1 weak#1; see docs/perf.md).
    fwd_flops, flops_backend = _fwd_flops_per_image(
        bundle, api.variables, ds.train_x.shape[2:], batch, jnp.bfloat16)
    train_flops = fwd_flops * 3.0 if fwd_flops else None
    peak, peak_entry = _peak_flops(jax.devices()[0])
    mfu = (round(padded_images / dt * train_flops / peak, 4)
           if (train_flops and peak) else None)

    # flagship attribution snapshot NOW, before the paradigm benches below
    # build their own programs: a cross-device FedAvgAPI is the same class,
    # so its host-path programs would overwrite the flagship's records
    # under the same names (tables were reset at attribution enable, so
    # everything recorded so far is the flagship's)
    flagship_tables = fedcost.cost_tables()
    # flagship profiler snapshot for the same reason: the paradigm benches
    # reuse client ids 0..31, which would merge into the flagship's profiles
    flagship_profiler = None
    if pulse_plane is not None:
        flagship_profiler = pulse_plane.aggregates()
        if pulse_plane.profiler is not None:
            pulse_plane.profiler.reset()
    # fedlens summary for the tail: the measured pass's update-norm/drift
    # sketch summaries (bench_report's `p99 update norm` / `drift p99`
    # columns read these) plus the session fold accounting. None when the
    # lens (or the pulse plane it feeds) is off — missing keys render "-".
    lens_summary = None
    if pulse_plane is not None and fedlens.lens_enabled():
        sk = (flagship_profiler or {}).get("sketches") or {}
        st = fedlens.session_stats()
        lens_summary = {"update_norm": sk.get("update_norm"),
                        "drift": sk.get("drift"),
                        "folds": st["folds"], "suspects": st["suspects"]}

    # fedpack flagship A/B (ISSUE 9): both packed-conv lowerings measured
    # through the same harness, embedded as the `packed_conv` block. Runs
    # AFTER the flagship snapshot (it resets the cost tables per arm) and
    # before the paradigm benches re-enable their own attribution records.
    packed_conv_ab = None
    if not os.environ.get("BENCH_NO_PACKED_AB"):
        packed_conv_ab = _bench_packed_conv_ab(ds, cfg, model, rounds, peak)
        fedcost.reset_cost_tables()   # paradigm benches attribute fresh

    # Cross-silo paradigm on the same hardware (VERDICT r2 #3): the north
    # star names DISTRIBUTED FedAvg, so measure the shard_map mesh path too —
    # full participation (the standard silo deployment), dataset resident and
    # sharded over a 1-device 'clients' mesh, aggregation by weighted psum.
    crosssilo = None
    if not os.environ.get("BENCH_NO_CROSSSILO"):
        crosssilo = _bench_crosssilo(tiny, model, rounds, batch)

    # Cross-device paradigm at the reference's 342,477-client scale
    # (VERDICT r4 #2): sampling + O(cohort) materialization + round.
    crossdevice = None
    if not os.environ.get("BENCH_NO_CROSSDEVICE"):
        crossdevice = _bench_crossdevice(tiny)

    # every HEADLINE program is built by now: snapshot the attribution and
    # switch it off BEFORE the weak-scaling probes re-run smaller configs —
    # cost_tables() keeps latest-wins per program name, so a probe rebuild
    # would overwrite the mesh entry with a shape the headline numbers were
    # never measured on. Disabling here also restores the process-global
    # flag for whoever runs after main() (the tier-1 tiny smoke).
    roofline_tables = fedcost.cost_tables()
    fedcost.enable_cost_attribution(False)

    # Weak-scaling regression pin (VERDICT r4 #8): measure T(c) at c=8/16
    # next to the 32-silo row above, fit T(c) = a + b*c through the
    # endpoints, and check the midpoint against the fit — model drift or a
    # perf regression in the mesh round shows up as a failed tolerance in
    # the artifact itself (docs/perf.md weak-scaling section).
    weak_scaling = None
    if (crosssilo and not tiny and crosssilo["clients"] > 16
            and not os.environ.get("BENCH_NO_WEAKSCALING")):
        c_hi = crosssilo["clients"]   # respect a BENCH_CS_CLIENTS override
        pts = {c_hi: 1.0 / crosssilo["rounds_per_sec"]}
        for c in (8, 16):
            row = _bench_crosssilo(tiny, model, rounds, batch,
                                   clients_override=c)
            pts[c] = 1.0 / row["rounds_per_sec"]
        b = (pts[c_hi] - pts[8]) / (c_hi - 8)
        a = pts[8] - b * 8
        pred16 = a + b * 16
        err = abs(pred16 - pts[16]) / pts[16]
        weak_scaling = {
            "round_seconds": {str(c): round(t, 4) for c, t in pts.items()},
            "fit_overhead_ms": round(a * 1e3, 2),
            "fit_per_silo_ms": round(b * 1e3, 2),
            "midpoint_pred_s": round(pred16, 4),
            "midpoint_err": round(err, 4),
            "ok": bool(err < 0.15),
        }
        if not weak_scaling["ok"]:
            import sys

            print(f"WEAK-SCALING DRIFT: midpoint error {err:.1%} exceeds "
                  f"15% — T(c) is no longer linear in silos; investigate",
                  file=sys.stderr)

    # End-of-run registry snapshot (fedml_tpu/obs): the time/wire/compile
    # counter groups land in the BENCH JSON tail, so the TPU-host trajectory
    # tracks compile amortization (program builds, LRU hits, first-call
    # trace+XLA ms) across PRs — not just wall-clock throughput.
    from fedml_tpu.obs import default_registry

    reg = default_registry()
    registry_snapshot = {}
    for ns in ("time", "wire", "compile"):
        snap = reg.snapshot(ns)
        if snap:
            registry_snapshot[ns] = {
                k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in snap.items()}

    # fedcost roofline block: the per-op lane table of every program this
    # run built, plus the flagship's flop-weighted MXU output-lane ceiling —
    # mfu above is judged AGAINST this ceiling, not against the datasheet
    # (docs/perf.md "MFU and the roofline"). Static attribution: the same
    # table tools/roofline_report.py derives, embedded so the TPU-host
    # trajectory carries it per PR.
    roofline = None
    tables = roofline_tables
    mfu_vs_lane_ceiling = None
    if tables or flagship_tables:
        # flagship entries win name collisions with the later paradigm
        # benches (same class -> same program names on the host path)
        tables = {**tables, **flagship_tables}
        roofline = {"programs": {}}
        for pname, rec in sorted(tables.items()):
            s = rec["summary"]
            roofline["programs"][pname] = {
                "shape_key": rec["shape_key"],
                "gemm_gflops_per_invocation": round(
                    s["gemm_flops_per_invocation"] / 1e9, 3),
                "out_lane_ceiling": s["out_lane_ceiling"],
                "red_lane_ceiling": s["red_lane_ceiling"],
                "by_output_channels": s["by_output_channels"],
                "top_ops": s["top_ops"][:5],
            }
        # the flagship program = the FLOP-dominant record of the flagship
        # pass (model-agnostic: packed, grouped, gather or host round)
        flag_rec = max(
            flagship_tables.values(),
            key=lambda r: r["summary"]["gemm_flops_per_invocation"],
            default=None)
        if flag_rec is not None:
            roofline["flagship_program"] = flag_rec["program"]
            roofline["flagship_out_lane_ceiling"] = \
                flag_rec["summary"]["out_lane_ceiling"]
            # MAC-basis MFU over the measured pass (obs/cost.roofline):
            # the `mfu` headline counts every HLO flop (BN/elementwise VPU
            # work included), which is NOT comparable to a GEMM-MAC lane
            # ceiling — dividing those would overstate the schedule's share
            # of what the lanes allow. One program x `rounds` invocations
            # is the dominant-program approximation (exact for the packed
            # default, where one program executes every round).
            rf = fedcost.roofline(flag_rec["summary"], dt,
                                  invocations=rounds, peak=peak)
            roofline["flagship_mfu_mac"] = rf["mfu_mac"]
            if "mfu_vs_ceiling" in rf:
                mfu_vs_lane_ceiling = rf["mfu_vs_ceiling"]

    result = {
        "metric": f"fedavg_local_sgd_images_per_sec ({model}, CIFAR-10 shapes, 32 non-IID clients, 8/round, bf16)",
        "value": round(img_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 3),
        "rounds_per_sec": round(rounds_per_sec, 4),
        "padded_images_per_sec": round(padded_images / dt, 1),
        "model_flops_per_image": round(train_flops) if train_flops else None,
        "mfu": mfu,
        "crosssilo": crosssilo,
        "crossdevice": crossdevice,
        "weak_scaling": weak_scaling,
        # mfu is an ESTIMATE: fwd FLOPs from XLA's cost model on the named
        # backend x3 for the train step, over the bf16 peak of the matched
        # spec-table entry — provenance recorded so a cost-model change or a
        # wrong peak-table substring match is visible in the JSON itself
        "mfu_basis": {"flops_cost_model_backend": flops_backend,
                      "fwd_bwd_multiplier": 3.0,
                      "peak_table_entry": peak_entry,
                      "peak_bf16_flops": peak},
        # MAC-basis MFU / lane ceiling: the schedule's share of what the
        # model's GEMM shapes allow (1.0 = lanes are the only limit) —
        # both sides of the division count GEMM multiply-accumulates only
        "mfu_vs_lane_ceiling": mfu_vs_lane_ceiling,
        # fedpack A/B (ops/packed_conv.py): per-lowering real img/s, the
        # packed program's lifted static lane ceiling, useful-basis MFU
        "packed_conv": packed_conv_ab,
        # fedpulse end-of-run profiler aggregates for the flagship pass
        # (the cross-device block embeds its own at 342k-client scale);
        # carries the fedsketch `sketches` summaries (count + p50/p90/p99
        # per lane) that bench_report's trajectory columns parse
        "profiler": flagship_profiler,
        # fedlens learning-signal tails at the flagship operating point
        "lens": lens_summary,
        "roofline": roofline,
        "registry": registry_snapshot,
        "device": str(jax.devices()[0]),
        # the comparability stamp (ISSUE 13): throughput numbers only mean
        # something against the same device/core-count/model basis —
        # bench_report's >10%-drop gate compares consecutive artifacts ONLY
        # when their bases match (a container/host change re-bases the
        # trajectory instead of reading as a regression; artifacts without
        # the stamp form their own legacy lineage)
        "host_basis": {"device": str(jax.devices()[0]),
                       "cpus": os.cpu_count(), "model": model},
    }
    print(json.dumps(result))


if __name__ == "__main__":
    # The TPU-tunnel compile service occasionally drops a long compile
    # (transient INTERNAL/remote_compile errors); one retry after a pause
    # rides through it rather than losing the whole bench run.
    try:
        main()
    except Exception as e:
        if not any(s in str(e) for s in ("INTERNAL", "remote_compile",
                                         "DEADLINE", "UNAVAILABLE")):
            raise
        import traceback

        traceback.print_exc()
        time.sleep(30)
        main()
